"""Tests for the descheduler analog: anomaly circuit breaker, sorter
ordering, eviction limiter, LowNodeLoad classification + balance planning,
migration arbitration + state machine."""

import numpy as np

from koordinator_tpu.descheduler import anomaly, evictions, lownodeload, migration, sorter
from koordinator_tpu.descheduler.anomaly import BasicDetector, State
from koordinator_tpu.descheduler.evictions import PodEvictor
from koordinator_tpu.descheduler.lownodeload import LowNodeLoadArgs, NodePool, balance, classify
from koordinator_tpu.model import resources as res

Gi = 1024**3


class TestAnomalyDetector:
    def test_trips_after_consecutive_abnormalities(self):
        t = [0.0]
        d = BasicDetector("n1", clock=lambda: t[0])
        for _ in range(5):
            assert d.mark(False) is State.OK
        assert d.mark(False) is State.ANOMALY  # 6th: > 5

    def test_recovers_after_consecutive_normalities(self):
        t = [0.0]
        d = BasicDetector("n1", clock=lambda: t[0])
        for _ in range(6):
            d.mark(False)
        assert d.state() is State.ANOMALY
        for _ in range(3):
            assert d.mark(True) is State.ANOMALY
        assert d.mark(True) is State.OK  # 4th: > 3

    def test_generation_timeout_clears_counts(self):
        t = [0.0]
        d = BasicDetector("n1", timeout_seconds=60, clock=lambda: t[0])
        for _ in range(5):
            d.mark(False)
        t[0] = 61.0  # counters roll
        for _ in range(5):
            assert d.mark(False) is State.OK
        assert d.mark(False) is State.ANOMALY

    def test_reset(self):
        d = BasicDetector("n1")
        for _ in range(6):
            d.mark(False)
        d.reset()
        assert d.state() is State.OK
        assert d.counter.total == 0


class TestSorter:
    def test_eviction_order_priority_first(self):
        pods = [
            {"name": "prod", "priority_class": "koord-prod", "priority": 9500},
            {"name": "batch-low-use", "priority_class": "koord-batch", "priority": 5500},
            {"name": "free", "priority_class": "koord-free", "priority": 3500},
        ]
        out = sorter.sort_pods_for_eviction(pods, {}, {"cpu": "10"}, {res.CPU: 1})
        assert [p["name"] for p in out] == ["free", "batch-low-use", "prod"]

    def test_usage_breaks_ties_high_first(self):
        pods = [
            {"name": "a", "priority": 5500},
            {"name": "b", "priority": 5500},
        ]
        metrics = {"a": {"cpu": "1"}, "b": {"cpu": "4"}}
        out = sorter.sort_pods_for_eviction(pods, metrics, {"cpu": "10"}, {res.CPU: 1})
        assert [p["name"] for p in out] == ["b", "a"]

    def test_qos_rank(self):
        pods = [
            {"name": "ls", "priority": 5500, "qos": "LS"},
            {"name": "be", "priority": 5500, "qos": "BE"},
        ]
        out = sorter.sort_pods_for_eviction(pods, {}, {"cpu": "10"}, {res.CPU: 1})
        assert out[0]["name"] == "be"


class TestPodEvictor:
    def test_per_node_cap(self):
        ev = PodEvictor(max_pods_per_node=1)
        assert ev.evict({"name": "a"}, "n1")
        assert not ev.evict({"name": "b"}, "n1")
        assert ev.evict({"name": "c"}, "n2")

    def test_per_namespace_cap(self):
        ev = PodEvictor(max_pods_per_namespace=1)
        assert ev.evict({"name": "a", "namespace": "x"}, "n1")
        assert not ev.evict({"name": "b", "namespace": "x"}, "n2")

    def test_rate_limiter(self):
        t = [0.0]
        ev = PodEvictor(qps=1.0, burst=2, clock=lambda: t[0])
        assert ev.evict({"name": "a"}, "n1")
        assert ev.evict({"name": "b"}, "n1")
        assert not ev.evict({"name": "c"}, "n1")
        t[0] = 1.1  # one token refilled
        assert ev.evict({"name": "c"}, "n1")

    def test_evict_fn_failure_not_counted(self):
        ev = PodEvictor(evict_fn=lambda pod, reason: False)
        assert not ev.evict({"name": "a"}, "n1")
        assert ev.total_evicted() == 0


class TestClassify:
    def test_under_over(self):
        usage = np.array([[10, 10], [50, 50], [95, 40]], dtype=np.int64)
        alloc = np.full((3, 2), 100, dtype=np.int64)
        cls = classify(
            ["a", "b", "c"], usage, alloc,
            low_pct=np.array([30.0, 30.0]), high_pct=np.array([80.0, 80.0]),
            use_deviation=False,
        )
        assert cls.underutilized.tolist() == [True, False, False]
        assert cls.overutilized.tolist() == [False, False, True]  # any-resource

    def test_unschedulable_never_underutilized(self):
        usage = np.array([[10, 10]], dtype=np.int64)
        alloc = np.full((1, 2), 100, dtype=np.int64)
        cls = classify(
            ["a"], usage, alloc,
            np.array([30.0, 30.0]), np.array([80.0, 80.0]),
            False, unschedulable=np.array([True]),
        )
        assert not cls.underutilized[0]

    def test_deviation_mode(self):
        # avg usage 50%; low=avg-10=40, high=avg+10=60
        usage = np.array([[30], [50], [70]], dtype=np.int64)
        alloc = np.full((3, 1), 100, dtype=np.int64)
        cls = classify(
            ["a", "b", "c"], usage, alloc,
            np.array([10.0]), np.array([10.0]), use_deviation=True,
        )
        assert cls.underutilized.tolist() == [True, False, False]
        assert cls.overutilized.tolist() == [False, False, True]


def _mk_node(name, cpu_used, pods=(), cpu_cap=100):
    return {
        "name": name,
        "allocatable": {"cpu": str(cpu_cap), "memory": 100 * Gi},
        "usage": {"cpu": str(cpu_used), "memory": 10 * Gi},
        "pods": list(pods),
    }


class TestBalance:
    def pool(self, **kw):
        kw.setdefault("low_thresholds", {res.CPU: 30, res.MEMORY: 30})
        kw.setdefault("high_thresholds", {res.CPU: 70, res.MEMORY: 70})
        return NodePool(**kw)

    def test_evicts_from_overutilized_until_under_threshold(self):
        hot_pods = [
            {"name": f"be-{i}", "priority": 5500, "qos": "BE", "usage": {"cpu": "10"}}
            for i in range(5)
        ]
        nodes = [
            _mk_node("cold", 10),
            _mk_node("hot", 90, pods=hot_pods),
            _mk_node("mid", 50),
        ]
        ev = PodEvictor()
        planned = balance(LowNodeLoadArgs(node_pools=[self.pool()]), nodes, ev)
        # 90 -> need to drop under 70: evict 2 pods of 10 cpu each (90->80->70)
        assert [p["pod"] for p in planned] == ["be-0", "be-1"]
        assert all(p["node"] == "hot" for p in planned)

    def test_no_low_nodes_no_evictions(self):
        nodes = [_mk_node("hot", 90, pods=[{"name": "p", "usage": {"cpu": "10"}}]), _mk_node("hot2", 85)]
        planned = balance(LowNodeLoadArgs(node_pools=[self.pool()]), nodes, PodEvictor())
        assert planned == []

    def test_all_low_nodes_no_evictions(self):
        nodes = [_mk_node("a", 5), _mk_node("b", 5)]
        planned = balance(LowNodeLoadArgs(node_pools=[self.pool()]), nodes, PodEvictor())
        assert planned == []

    def test_anomaly_debounce(self):
        hot = _mk_node("hot", 90, pods=[{"name": "p", "priority": 5500, "usage": {"cpu": "30"}}])
        nodes = [_mk_node("cold", 10), hot, _mk_node("mid", 50)]
        pool = self.pool(consecutive_abnormalities=3)
        detectors = {}
        args = LowNodeLoadArgs(node_pools=[pool])
        # ticks 1-3: counter accumulating (needs > 3)
        for _ in range(3):
            assert balance(args, nodes, PodEvictor(), detectors) == []
        planned = balance(args, nodes, PodEvictor(), detectors)
        assert len(planned) == 1

    def test_headroom_limits_evictions(self):
        # low=60/high=70: cold node (59) headroom = 70 - 59 = 11 cpu; two
        # 10-cpu evictions exhaust it while "hot" (95 -> 75) is still over.
        hot_pods = [
            {"name": f"be-{i}", "priority": 5500, "usage": {"cpu": "10"}} for i in range(5)
        ]
        pool = self.pool(
            low_thresholds={res.CPU: 60, res.MEMORY: 60},
            high_thresholds={res.CPU: 70, res.MEMORY: 70},
        )
        nodes = [_mk_node("cold", 59), _mk_node("hot", 95, pods=hot_pods), _mk_node("mid", 65)]
        planned = balance(LowNodeLoadArgs(node_pools=[pool]), nodes, PodEvictor())
        assert len(planned) == 2

    def test_pool_selector(self):
        hot = _mk_node("hot", 90, pods=[{"name": "p", "usage": {"cpu": "30"}}])
        hot["labels"] = {"pool": "other"}
        cold = _mk_node("cold", 10)
        cold["labels"] = {"pool": "other"}
        pool = self.pool(node_selector={"pool": "mine"})
        planned = balance(LowNodeLoadArgs(node_pools=[pool]), [hot, cold], PodEvictor())
        assert planned == []


class TestMigration:
    def test_arbitration_per_node_cap(self):
        args = migration.MigrationControllerArgs(max_concurrent_reclaims_per_node=1)
        ctrl = migration.MigrationController(args=args, evict=lambda pod: True)
        ctrl.create_reservation = lambda job: "r-" + job.name
        ctrl.reservation_bound = lambda name: True
        ctrl.submit(migration.MigrationJob("j1", {"name": "a", "node": "n1"}, creation_time=0))
        ctrl.submit(migration.MigrationJob("j2", {"name": "b", "node": "n1"}, creation_time=1))
        ctrl.reconcile(now=1.0)
        j1, j2 = ctrl.jobs["j1"], ctrl.jobs["j2"]
        assert j1.phase == migration.SUCCEEDED
        # j2 blocked this round by the per-node cap while j1 was active
        assert j2.phase == migration.PENDING
        ctrl.reconcile(now=2.0)
        assert ctrl.jobs["j2"].phase == migration.SUCCEEDED

    def test_reservation_first_waits_for_bound(self):
        bound = {"r-j1": False}
        ctrl = migration.MigrationController(
            create_reservation=lambda job: "r-" + job.name,
            reservation_bound=lambda name: bound[name],
            evict=lambda pod: True,
        )
        ctrl.submit(migration.MigrationJob("j1", {"name": "a", "node": "n1"}))
        ctrl.reconcile(now=0.0)
        assert ctrl.jobs["j1"].phase == migration.RUNNING
        assert ctrl.jobs["j1"].reason == migration.REASON_WAIT_RESERVATION
        bound["r-j1"] = True
        ctrl.reconcile(now=1.0)
        assert ctrl.jobs["j1"].phase == migration.SUCCEEDED

    def test_ttl_timeout(self):
        ctrl = migration.MigrationController(
            args=migration.MigrationControllerArgs(default_job_ttl_seconds=10),
            create_reservation=lambda job: None,
        )
        ctrl.submit(migration.MigrationJob("j1", {"name": "a"}, creation_time=0.0))
        ctrl.jobs["j1"].phase = migration.PENDING
        ctrl.jobs["j1"].passed_arbitration = True  # stuck in queue
        ctrl.reconcile(now=100.0)
        assert ctrl.jobs["j1"].phase == migration.FAILED
        assert ctrl.jobs["j1"].reason == migration.REASON_TIMEOUT

    def test_evict_directly_mode(self):
        ctrl = migration.MigrationController(
            args=migration.MigrationControllerArgs(default_job_mode="EvictDirectly"),
            evict=lambda pod: True,
        )
        ctrl.submit(migration.MigrationJob("j1", {"name": "a", "node": "n1"}))
        ctrl.reconcile(now=0.0)
        assert ctrl.jobs["j1"].phase == migration.SUCCEEDED

    def test_scavenge(self):
        ctrl = migration.MigrationController(evict=lambda pod: True)
        ctrl.submit(migration.MigrationJob("j1", {"name": "a"}, creation_time=0.0, mode="EvictDirectly"))
        ctrl.reconcile(now=0.0)
        assert ctrl.scavenge(now=1000.0) == 1
        assert not ctrl.jobs


class TestReviewRegressions:
    def _pool(self, **kw):
        kw.setdefault("low_thresholds", {res.CPU: 30, res.MEMORY: 30})
        kw.setdefault("high_thresholds", {res.CPU: 70, res.MEMORY: 70})
        return NodePool(**kw)

    def test_dry_run_skips_evictor(self):
        calls = []
        hot_pods = [{"name": "p", "priority": 5500, "usage": {"cpu": "30"}}]
        nodes = [_mk_node("cold", 10), _mk_node("hot", 90, pods=hot_pods), _mk_node("mid", 50)]
        ev = PodEvictor(evict_fn=lambda pod, reason: calls.append(pod) or True)
        planned = balance(LowNodeLoadArgs(node_pools=[self._pool()], dry_run=True), nodes, ev)
        assert len(planned) == 1
        assert calls == [] and ev.total_evicted() == 0

    def test_node_fit_blocks_oversized_pods(self):
        # pod requests 90 cpu: no destination headroom fits it
        hot_pods = [{"name": "big", "priority": 5500, "usage": {"cpu": "30"},
                     "requests": {"cpu": "90"}}]
        nodes = [_mk_node("cold", 10), _mk_node("hot", 90, pods=hot_pods), _mk_node("mid", 50)]
        planned = balance(LowNodeLoadArgs(node_pools=[self._pool()], node_fit=True), nodes, PodEvictor())
        assert planned == []
        planned = balance(LowNodeLoadArgs(node_pools=[self._pool()], node_fit=False), nodes, PodEvictor())
        assert len(planned) == 1

    def test_guard_exit_leaves_nodes_for_next_pool(self):
        # pool A (all nodes cold for its thresholds) trips a guard; pool B
        # must still process the same nodes and evict.
        hot_pods = [{"name": "p", "priority": 5500, "usage": {"cpu": "30"}}]
        nodes = [_mk_node("cold", 10), _mk_node("hot", 90, pods=hot_pods), _mk_node("mid", 50)]
        pool_a = self._pool(
            name="a",
            low_thresholds={res.CPU: 99, res.MEMORY: 99},
            high_thresholds={res.CPU: 99, res.MEMORY: 99},
        )  # all nodes underutilized -> guard exit
        pool_b = self._pool(name="b")
        planned = balance(LowNodeLoadArgs(node_pools=[pool_a, pool_b]), nodes, PodEvictor())
        assert [p["pool"] for p in planned] == ["b"]

    def test_simulated_time_rolls_detector_generation(self):
        from koordinator_tpu.descheduler.anomaly import BasicDetector, State
        d = BasicDetector("n", timeout_seconds=60, clock=lambda: 0.0)
        for _ in range(5):
            d.mark(False, now=0.0)
        assert d.mark(False, now=100.0) is State.OK  # generation rolled


class TestNodeTaints:
    """RemovePodsViolatingNodeTaints (ref framework/plugins/kubernetes,
    upstream sigs.k8s.io/descheduler nodetaints semantics)."""

    NODES = [
        {"name": "n0", "taints": [
            {"key": "dedicated", "value": "infra", "effect": "NoSchedule"}]},
        {"name": "n1", "taints": []},
    ]

    def test_untolerated_pod_selected(self):
        from koordinator_tpu.descheduler.k8s_plugins import (
            remove_pods_violating_node_taints,
        )

        pods = [
            {"name": "a", "node": "n0", "tolerations": []},
            {"name": "b", "node": "n0", "tolerations": [
                {"key": "dedicated", "operator": "Equal", "value": "infra",
                 "effect": "NoSchedule"}]},
            {"name": "c", "node": "n1", "tolerations": []},
        ]
        got = remove_pods_violating_node_taints(pods, self.NODES)
        assert [p["name"] for p in got] == ["a"]

    def test_exists_and_empty_key_tolerations(self):
        from koordinator_tpu.descheduler.k8s_plugins import (
            remove_pods_violating_node_taints,
        )

        pods = [
            {"name": "exists", "node": "n0", "tolerations": [
                {"key": "dedicated", "operator": "Exists"}]},
            {"name": "wildcard", "node": "n0", "tolerations": [
                {"operator": "Exists"}]},
            {"name": "wrong-value", "node": "n0", "tolerations": [
                {"key": "dedicated", "value": "web"}]},
        ]
        got = remove_pods_violating_node_taints(pods, self.NODES)
        assert [p["name"] for p in got] == ["wrong-value"]

    def test_excluded_taints_and_prefer_no_schedule(self):
        from koordinator_tpu.descheduler.k8s_plugins import (
            NodeTaintsArgs,
            remove_pods_violating_node_taints,
        )

        nodes = [
            {"name": "n0", "taints": [
                {"key": "soft", "effect": "PreferNoSchedule"}]},
        ]
        pods = [{"name": "p", "node": "n0", "tolerations": []}]
        assert remove_pods_violating_node_taints(pods, nodes) == []
        got = remove_pods_violating_node_taints(
            pods, nodes, NodeTaintsArgs(include_prefer_no_schedule=True)
        )
        assert len(got) == 1
        got = remove_pods_violating_node_taints(
            pods,
            nodes,
            NodeTaintsArgs(
                include_prefer_no_schedule=True, excluded_taints=("soft",)
            ),
        )
        assert got == []


class TestRemoveFailedPods:
    def test_failed_selected_with_reason_and_age_gates(self):
        from koordinator_tpu.descheduler.k8s_plugins import (
            FailedPodsArgs,
            remove_failed_pods,
        )

        pods = [
            {"name": "oom", "phase": "Failed", "reason": "OOMKilled",
             "start_time": 0.0},
            {"name": "young", "phase": "Failed", "reason": "OOMKilled",
             "start_time": 95.0},
            {"name": "other", "phase": "Failed", "reason": "Evicted",
             "start_time": 0.0},
            {"name": "running", "phase": "Running"},
        ]
        got = remove_failed_pods(
            pods,
            FailedPodsArgs(
                reasons=("OOMKilled",), min_pod_lifetime_seconds=60
            ),
            now=100.0,
        )
        assert [p["name"] for p in got] == ["oom"]

    def test_owner_kind_exclusion_and_container_reasons(self):
        from koordinator_tpu.descheduler.k8s_plugins import (
            FailedPodsArgs,
            remove_failed_pods,
        )

        pods = [
            {"name": "job-pod", "phase": "Failed",
             "owner_references": [{"kind": "Job"}],
             "containers": [{"reason": "CrashLoopBackOff"}]},
        ]
        assert remove_failed_pods(
            pods, FailedPodsArgs(exclude_owner_kinds=("Job",))
        ) == []
        got = remove_failed_pods(
            pods, FailedPodsArgs(reasons=("CrashLoopBackOff",))
        )
        assert len(got) == 1


class TestPodLifeTime:
    def test_age_state_and_label_gates(self):
        from koordinator_tpu.descheduler.k8s_plugins import (
            PodLifeTimeArgs,
            pod_life_time,
        )

        pods = [
            {"name": "old", "start_time": 0.0, "phase": "Running",
             "labels": {"app": "x"}},
            {"name": "new", "start_time": 900.0, "phase": "Running",
             "labels": {"app": "x"}},
            {"name": "old-pending", "start_time": 0.0, "phase": "Pending",
             "labels": {"app": "x"}},
            {"name": "old-other", "start_time": 0.0, "phase": "Running",
             "labels": {"app": "y"}},
        ]
        got = pod_life_time(
            pods,
            PodLifeTimeArgs(
                max_pod_life_time_seconds=600,
                states=("Running",),
                label_selector={"app": "x"},
            ),
            now=1000.0,
        )
        assert [p["name"] for p in got] == ["old"]


class TestTopologySpread:
    def _cluster(self, counts):
        nodes = [
            {"name": f"n{i}", "labels": {"zone": f"z{i}"}}
            for i in range(len(counts))
        ]
        pods = []
        for i, c in enumerate(counts):
            for j in range(c):
                pods.append(
                    {
                        "name": f"p{i}-{j}",
                        "node": f"n{i}",
                        "labels": {"app": "web"},
                        "topology_spread": [
                            {
                                "max_skew": 1,
                                "topology_key": "zone",
                                "when_unsatisfiable": "DoNotSchedule",
                                "label_selector": {"app": "web"},
                            }
                        ],
                    }
                )
        return pods, nodes

    def test_balances_skew_to_max(self):
        from koordinator_tpu.descheduler.k8s_plugins import (
            remove_pods_violating_topology_spread,
        )

        pods, nodes = self._cluster([5, 1, 0])
        got = remove_pods_violating_topology_spread(pods, nodes)
        # 5/1/0 -> move until max-min <= 1: (4,1,1)->(3,2,1)->(2,2,2) = 3 moves
        assert len(got) == 3
        assert all(p["node"] == "n0" for p in got)

    def test_within_skew_selects_nothing(self):
        from koordinator_tpu.descheduler.k8s_plugins import (
            remove_pods_violating_topology_spread,
        )

        pods, nodes = self._cluster([2, 1, 2])
        assert remove_pods_violating_topology_spread(pods, nodes) == []

    def test_soft_constraints_gated(self):
        from koordinator_tpu.descheduler.k8s_plugins import (
            TopologySpreadArgs,
            remove_pods_violating_topology_spread,
        )

        pods, nodes = self._cluster([4, 0])
        for p in pods:
            p["topology_spread"][0]["when_unsatisfiable"] = "ScheduleAnyway"
        assert remove_pods_violating_topology_spread(pods, nodes) == []
        got = remove_pods_violating_topology_spread(
            pods, nodes, TopologySpreadArgs(include_soft_constraints=True)
        )
        assert len(got) > 0


class TestNewAdaptorsRegistered:
    def test_profile_runs_all_new_plugins(self):
        from koordinator_tpu.descheduler.evictions import PodEvictor
        from koordinator_tpu.descheduler.runtime import (
            Descheduler,
            DeschedulerProfile,
            PluginSet,
        )

        nodes = [
            {
                "name": "n0",
                "taints": [{"key": "dedicated", "effect": "NoSchedule"}],
                "pods": [
                    {"name": "tainted", "node": "n0", "tolerations": [],
                     "owner_references": [{"kind": "ReplicaSet"}]},
                    {"name": "failed", "node": "n0", "phase": "Failed",
                     "owner_references": [{"kind": "Job"}]},
                ],
            },
            {"name": "n1", "taints": [], "pods": []},
        ]
        evictor = PodEvictor(dry_run=True)
        d = Descheduler(
            [
                DeschedulerProfile(
                    plugins=PluginSet(
                        deschedule=[
                            "RemovePodsViolatingNodeTaints",
                            "RemoveFailedPods",
                            "PodLifeTime",
                            "RemovePodsViolatingTopologySpreadConstraint",
                        ]
                    )
                )
            ],
            nodes_fn=lambda: nodes,
            evictor=evictor,
        )
        d.descheduler_once()
        evicted = {e.pod for e in evictor.evicted}
        assert {"tainted", "failed"} <= evicted


class TestReviewRegressionsRound4:
    def test_topology_spread_cluster_wide_through_registry(self):
        """A balanced cluster must select nothing when the plugin runs
        through the registry (a per-node view would see (3,0) skew)."""
        from koordinator_tpu.descheduler.evictions import PodEvictor
        from koordinator_tpu.descheduler.runtime import (
            Descheduler,
            DeschedulerProfile,
            PluginSet,
        )

        spread = [{"max_skew": 1, "topology_key": "zone",
                   "when_unsatisfiable": "DoNotSchedule",
                   "label_selector": {"app": "web"}}]
        nodes = [
            {"name": f"n{i}", "labels": {"zone": f"z{i}"},
             "pods": [
                 {"name": f"p{i}-{j}", "node": f"n{i}",
                  "labels": {"app": "web"}, "topology_spread": spread}
                 for j in range(3)
             ]}
            for i in range(2)
        ]
        evictor = PodEvictor(dry_run=True)
        d = Descheduler(
            [DeschedulerProfile(plugins=PluginSet(
                deschedule=["RemovePodsViolatingTopologySpreadConstraint"]))],
            nodes_fn=lambda: nodes,
            evictor=evictor,
        )
        d.descheduler_once()
        assert evictor.evicted == []

    def test_unsatisfiable_zero_skew_selects_nothing(self):
        from koordinator_tpu.descheduler.k8s_plugins import (
            remove_pods_violating_topology_spread,
        )

        spread = [{"max_skew": 0, "topology_key": "zone",
                   "when_unsatisfiable": "DoNotSchedule",
                   "label_selector": {"app": "w"}}]
        nodes = [{"name": "n0", "labels": {"zone": "a"}},
                 {"name": "n1", "labels": {"zone": "b"}}]
        pods = [
            {"name": "p0", "node": "n0", "labels": {"app": "w"},
             "topology_spread": spread},
            {"name": "p1", "node": "n0", "labels": {"app": "w"},
             "topology_spread": spread},
            {"name": "p2", "node": "n1", "labels": {"app": "w"},
             "topology_spread": spread},
        ]
        assert remove_pods_violating_topology_spread(pods, nodes) == []

    def test_unknown_age_pods_never_selected_by_age_gates(self):
        from koordinator_tpu.descheduler.k8s_plugins import (
            FailedPodsArgs,
            PodLifeTimeArgs,
            pod_life_time,
            remove_failed_pods,
        )

        ageless = [{"name": "p", "phase": "Running", "labels": {}}]
        assert pod_life_time(
            ageless, PodLifeTimeArgs(max_pod_life_time_seconds=60),
            now=1.7e9,
        ) == []
        failed_ageless = [{"name": "f", "phase": "Failed"}]
        assert remove_failed_pods(
            failed_ageless,
            FailedPodsArgs(min_pod_lifetime_seconds=60),
            now=1.7e9,
        ) == []
        # without an age gate a Failed pod is still selected
        assert len(remove_failed_pods(failed_ageless, FailedPodsArgs())) == 1
