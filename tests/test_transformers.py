"""Informer-level transformers (pkg/util/transformer): deprecated resource
renames, node-reservation allocatable trim, informer field drop."""

from koordinator_tpu.transformers import (
    transform_cluster,
    transform_elastic_quota,
    transform_node,
    transform_pod,
)


class TestPodTransform:
    def test_deprecated_batch_renamed(self):
        pod = {
            "name": "p",
            "requests": {"koordinator.sh/batch-cpu": "4000", "memory": 1024},
            "limits": {"koordinator.sh/batch-memory": "2Gi"},
        }
        out = transform_pod(pod)
        assert out["requests"] == {
            "kubernetes.io/batch-cpu": "4000",
            "memory": 1024,
        }
        assert out["limits"] == {"kubernetes.io/batch-memory": "2Gi"}

    def test_canonical_wins_when_both_present(self):
        pod = {
            "name": "p",
            "requests": {
                "koordinator.sh/batch-cpu": "1000",
                "kubernetes.io/batch-cpu": "2000",
            },
        }
        out = transform_pod(pod)
        # replaceAndErase: the deprecated entry is erased, never overwrites
        assert out["requests"] == {"kubernetes.io/batch-cpu": "2000"}

    def test_deprecated_device_renamed(self):
        pod = {"name": "p", "requests": {"kubernetes.io/gpu-core": 100}}
        out = transform_pod(pod)
        assert out["requests"] == {"koordinator.sh/gpu-core": 100}

    def test_trim_fields_dropped(self):
        out = transform_pod({"name": "p", "managed_fields": [{"huge": 1}]})
        assert "managed_fields" not in out


class TestNodeTransform:
    def test_reservation_trims_allocatable(self):
        node = {
            "name": "n",
            "allocatable": {"cpu": "16000m", "memory": "65536Mi"},
            "annotations": {
                "node.koordinator.sh/reservation": (
                    '{"resources": {"cpu": "2000m", "memory": "4096Mi"}}'
                )
            },
        }
        out = transform_node(node)
        assert out["allocatable"]["cpu"] == "14000m"
        assert out["allocatable"]["memory"] == "61440Mi"

    def test_non_default_apply_policy_skips_trim(self):
        node = {
            "name": "n",
            "allocatable": {"cpu": "16000m"},
            "annotations": {
                "node.koordinator.sh/reservation": (
                    '{"resources": {"cpu": "2000m"},'
                    ' "applyPolicy": "ReservedCPUsOnly"}'
                )
            },
        }
        assert transform_node(node)["allocatable"]["cpu"] == "16000m"

    def test_trim_never_negative(self):
        node = {
            "name": "n",
            "allocatable": {"cpu": "1000m"},
            "annotations": {
                "node.koordinator.sh/reservation": (
                    '{"resources": {"cpu": "2000m"}}'
                )
            },
        }
        assert transform_node(node)["allocatable"]["cpu"] == "0m"

    def test_bad_annotation_keeps_node(self):
        node = {
            "name": "n",
            "allocatable": {"cpu": "1000m"},
            "annotations": {"node.koordinator.sh/reservation": "{broken"},
        }
        assert transform_node(node)["allocatable"]["cpu"] == "1000m"


class TestQuotaAndCluster:
    def test_quota_min_max_renamed(self):
        q = {
            "name": "q",
            "min": {"koordinator.sh/batch-cpu": "1000"},
            "max": {"kubernetes.io/rdma": 2},
        }
        out = transform_elastic_quota(q)
        assert out["min"] == {"kubernetes.io/batch-cpu": "1000"}
        assert out["max"] == {"koordinator.sh/rdma": 2}

    def test_transform_cluster_feeds_encode(self):
        from koordinator_tpu.model import encode_snapshot, resources as res
        import numpy as np

        nodes = [
            {
                "name": "n0",
                "allocatable": {"cpu": "8000m", "memory": "32768Mi"},
                "annotations": {
                    "node.koordinator.sh/reservation": (
                        '{"resources": {"cpu": "1000m"}}'
                    )
                },
            }
        ]
        pods = [
            {
                "name": "p0",
                "requests": {"koordinator.sh/batch-cpu": "500"},
            }
        ]
        tn, tp, _ = transform_cluster(nodes, pods)
        snap = encode_snapshot(tn, tp)
        cpu = res.RESOURCE_INDEX[res.CPU]
        bcpu = res.RESOURCE_INDEX[res.BATCH_CPU]
        assert int(np.asarray(snap.nodes.allocatable)[0, cpu]) == 7000
        assert int(np.asarray(snap.pods.requests)[0, bcpu]) == 500
