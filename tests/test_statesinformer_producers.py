"""statesinformer producer half (round-3 review #6): the NRT and Device
reporters publish through the informer plugin registry, and the
scheduler's zone extras are constructed FROM the published reports
(reference impl/states_noderesourcetopology.go, impl/registry.go).
"""

import os

import numpy as np

from koordinator_tpu.koordlet.statesinformer import (
    DeviceReporter,
    NodeTopoReporter,
    StatesInformer,
    zones_from_node_topos,
)
from koordinator_tpu.koordlet.sysfs import CgroupVersion, SysFS
from koordinator_tpu.model.topology import encode_zones


def write_sysfs_topology(root, numa_nodes=2, cores_per_node=2, threads=2,
                         mem_bytes_per_node=4 << 30):
    """Fake /sys tree: <numa_nodes> NUMA nodes x <cores_per_node> cores x
    <threads> SMT threads, contiguous cpu ids per core."""
    cpu = 0
    for n in range(numa_nodes):
        nd = os.path.join(root, "sys", "devices", "system", "node", f"node{n}")
        os.makedirs(nd, exist_ok=True)
        first = cpu
        last = cpu + cores_per_node * threads - 1
        with open(os.path.join(nd, "cpulist"), "w") as f:
            f.write(f"{first}-{last}\n")
        with open(os.path.join(nd, "meminfo"), "w") as f:
            f.write(f"Node {n} MemTotal: {mem_bytes_per_node // 1024} kB\n")
        for c in range(cores_per_node):
            core_id = n * cores_per_node + c
            for _t in range(threads):
                cd = os.path.join(
                    root, "sys", "devices", "system", "cpu", f"cpu{cpu}",
                    "topology",
                )
                os.makedirs(cd, exist_ok=True)
                with open(os.path.join(cd, "core_id"), "w") as f:
                    f.write(f"{core_id}\n")
                with open(os.path.join(cd, "physical_package_id"), "w") as f:
                    f.write("0\n")
                cpu += 1


class TestSysfsTopology:
    def test_cpu_topology_and_numa_memory(self, tmp_path):
        write_sysfs_topology(str(tmp_path))
        fs = SysFS(root=str(tmp_path), cgroup_version=CgroupVersion.V1)
        topo = fs.cpu_topology()
        assert len(topo) == 8  # 2 numa x 2 cores x 2 threads
        # cpus 0-3 on numa 0, 4-7 on numa 1
        assert [t[2] for t in topo] == [0, 0, 0, 0, 1, 1, 1, 1]
        # siblings share a core id
        assert topo[0][1] == topo[1][1] and topo[2][1] == topo[3][1]
        assert fs.numa_nodes() == [0, 1]
        assert fs.numa_node_memory_bytes(0) == 4 << 30

    def test_parse_cpulist_forms(self):
        assert SysFS._parse_cpulist("0-3,8,10-11") == [0, 1, 2, 3, 8, 10, 11]
        assert SysFS._parse_cpulist("") == []


class TestNodeTopoReporter:
    def test_publishes_nrt_through_informer(self, tmp_path):
        write_sysfs_topology(str(tmp_path))
        fs = SysFS(root=str(tmp_path), cgroup_version=CgroupVersion.V1)
        informer = StatesInformer()
        reporter = NodeTopoReporter(fs, informer, node_name="n0")
        informer.register_plugin(reporter)
        reports = informer.sync_plugins(now=0.0)
        nrt = reports["nodetopo"]
        assert nrt is not None and informer.get_node_topo() == nrt
        assert [z["name"] for z in nrt["zones"]] == ["node-0", "node-1"]
        assert nrt["zones"][0]["resources"]["cpu"] == "4000m"
        assert nrt["zones"][0]["resources"]["memory"] == 4 << 30
        assert len(nrt["cpuTopology"]["detail"]) == 8

    def test_empty_sysfs_publishes_nothing(self, tmp_path):
        fs = SysFS(root=str(tmp_path), cgroup_version=CgroupVersion.V1)
        informer = StatesInformer()
        reporter = NodeTopoReporter(fs, informer)
        assert reporter.sync(0.0) is None
        assert informer.get_node_topo() == {}


class TestDeviceReporter:
    def test_publishes_device_cr(self):
        informer = StatesInformer()
        devices = [
            {"type": "tpu", "minor": 0, "numa_node": 0,
             "resources": {"koordinator.sh/gpu-core": 100}},
            {"type": "tpu", "minor": 1, "numa_node": 1, "resources": {}},
        ]
        reporter = DeviceReporter(informer, devices_fn=lambda: devices)
        out = reporter.sync(0.0)
        assert len(out) == 2
        got = informer.get_devices()
        assert got[1]["topology"]["numaNode"] == 1
        assert got[0]["health"] is True


class TestProducerToSchedulerChain:
    def test_published_nrt_feeds_zone_extras(self, tmp_path):
        """The consumer half (ops/numa zone kernels) runs on a ZoneBatch
        built from PUBLISHED reports, not hand-built fixtures."""
        from koordinator_tpu.ops.numa import zone_fit_mask

        roots = []
        topos = []
        for i in range(2):
            root = os.path.join(str(tmp_path), f"host{i}")
            write_sysfs_topology(
                root, numa_nodes=2, cores_per_node=2 + i, threads=2
            )
            roots.append(root)
            fs = SysFS(root=root, cgroup_version=CgroupVersion.V1)
            informer = StatesInformer()
            rep = NodeTopoReporter(fs, informer, node_name=f"n{i}")
            informer.register_plugin(rep)
            informer.sync_plugins(0.0)
            topos.append(informer.get_node_topo())

        zb = encode_zones(zones_from_node_topos(topos), node_bucket=2)
        alloc = np.asarray(zb.allocatable)
        # node 0: 2 cores x 2 threads = 4000m per zone; node 1: 6000m
        assert alloc[0, 0, 0] == 4000 and alloc[1, 0, 0] == 6000
        assert np.asarray(zb.valid).sum() == 4

        # a pod needing 5000m fits only node 1's zones
        reqs = np.zeros((1, alloc.shape[2]), np.int64)
        reqs[0, 0] = 5000
        import jax.numpy as jnp

        fits = np.asarray(
            zone_fit_mask(
                jnp.asarray(reqs), zb.allocatable, zb.requested, zb.valid
            )
        )
        assert not fits[0, 0].any() and fits[0, 1].any()


class TestDeviceProducerToScheduler:
    def test_published_device_crs_feed_encode_devices(self):
        from koordinator_tpu.koordlet.statesinformer import (
            device_nodes_from_informers,
        )
        from koordinator_tpu.model.device import encode_devices

        informer = StatesInformer()
        DeviceReporter(
            informer,
            devices_fn=lambda: [
                {"minor": 0, "platform": "tpu", "numa_node": 1,
                 "resources": {"koordinator.sh/gpu-core": 100}},
                {"minor": 1, "platform": "cpu"},  # filtered
            ],
        ).sync(0.0)
        batch = encode_devices(
            device_nodes_from_informers([informer.get_devices()]),
            node_bucket=1,
        )
        assert int(np.asarray(batch.valid).sum()) == 1
        assert int(np.asarray(batch.numa)[0, 0]) == 1
        assert int(np.asarray(batch.total)[0, 0, 0]) == 100

    def test_unhealthy_device_keeps_slot_invalid(self):
        """An unhealthy minor must NOT renumber its neighbors: slot index
        is the device identity the Reserve path reports back."""
        from koordinator_tpu.koordlet.statesinformer import (
            device_nodes_from_informers,
        )
        from koordinator_tpu.model.device import encode_devices

        nodes = device_nodes_from_informers(
            [[
                {"type": "gpu", "minor": 0, "health": False,
                 "resources": {"koordinator.sh/gpu-core": 100}},
                {"type": "gpu", "minor": 1, "health": True,
                 "resources": {"koordinator.sh/gpu-core": 100}},
            ]]
        )
        batch = encode_devices(nodes, node_bucket=1)
        valid = np.asarray(batch.valid)[0]
        # minor 1 stays at slot 1; slot 0 is present but invalid
        assert not valid[0] and valid[1]
