"""Distributed tracing (ISSUE 14): span identity/links, the export
sink's bounds, offline assembly, and the cross-process end-to-end —
one request, one tree, across client + server + coalesced launch.

The chaos-side acceptance (across a leader kill, brownout window,
retries) lives in tests/test_chaos_trace.py; this file owns the unit
surfaces and the happy-path integration.
"""

import json
import os

import pytest

from koordinator_tpu.obs import assemble as assemble_mod
from koordinator_tpu.obs.export import SpanExporter, resolve_export_dir
from koordinator_tpu.obs.spans import (
    ClientTraceOp,
    LINK_FANIN,
    SpanRecorder,
    TraceSpan,
    mint_span_id,
    mint_trace_id,
)


class TestTraceSpan:
    def test_record_shape_and_links(self):
        sink = []
        span = TraceSpan(
            "score", "t" * 32, "abcd", parent_id="p1", kind="server",
            sink=sink.append, attrs={"band": "koord-prod"},
        )
        span.link("u" * 32, "launch1")
        span.set_attr("top_k", 8)
        span.end()
        assert len(sink) == 1
        rec = sink[0]
        assert rec["traceId"] == "t" * 32
        assert rec["spanId"] == "abcd"
        assert rec["parentSpanId"] == "p1"
        assert rec["kind"] == "server"
        assert rec["status"] == {"code": "OK"}
        assert rec["attributes"] == {"band": "koord-prod", "top_k": 8}
        assert rec["links"] == [
            {"traceId": "u" * 32, "spanId": "launch1",
             "type": LINK_FANIN}
        ]
        assert rec["endTimeUnixNano"] >= rec["startTimeUnixNano"]

    def test_end_is_idempotent_and_abort_wins_first(self):
        sink = []
        span = TraceSpan("x", "t1", "s1", sink=sink.append)
        span.abort(RuntimeError("boom"))
        span.end()  # the finally-after-abort shape must not re-export
        assert len(sink) == 1
        assert sink[0]["status"]["code"] == "ERROR"
        assert "boom" in sink[0]["status"]["message"]

    def test_context_manager_aborts_on_exception(self):
        sink = []
        with pytest.raises(ValueError):
            with TraceSpan("x", "t1", "s2", sink=sink.append):
                raise ValueError("inner")
        assert sink[0]["status"]["code"] == "ERROR"

    def test_link_ref_none_is_noop(self):
        span = TraceSpan("x", "t1", "s3")
        span.link_ref(None)
        assert span.links == []

    def test_recorder_span_ids_deterministic_under_pinned_epoch(self):
        rec = SpanRecorder(epoch="feedf00d")
        assert rec.mint_span_id() == "spfeedf00d-1"
        assert rec.mint_span_id() == "spfeedf00d-2"
        # empty trace id = tracing off for this request: no span
        assert rec.start_trace_span("score", "") is None
        span = rec.start_trace_span("score", "t" * 32)
        assert span is not None and span.span_id == "spfeedf00d-3"
        span.end()

    def test_client_op_one_trace_per_logical_request(self):
        sink = []
        op = ClientTraceOp("score", sink=sink.append)
        a1 = op.attempt("replica-0")
        a1.abort(RuntimeError("shed"))
        a2 = op.attempt("replica-1")
        a2.set_attr("server_span", "sp1")
        a2.end()
        op.finish()
        assert len(sink) == 3
        trace_ids = {r["traceId"] for r in sink}
        assert trace_ids == {op.trace_id}  # ONE trace
        attempts = [r for r in sink if r["name"] == "score.attempt"]
        assert [r["attributes"]["attempt"] for r in attempts] == [1, 2]
        root = [r for r in sink if r["name"] == "score"][0]
        assert all(
            r["parentSpanId"] == root["spanId"] for r in attempts
        )
        assert root["attributes"]["attempts"] == 2

    def test_ids_are_unique(self):
        assert mint_trace_id() != mint_trace_id()
        assert len(mint_trace_id()) == 32
        assert len(mint_span_id()) == 16


class TestSpanExporter:
    def _record(self, i=0):
        return {
            "traceId": "t" * 32, "spanId": f"s{i}", "name": "x",
            "kind": "server", "startTimeUnixNano": 1, "durMs": 0.1,
        }

    def test_appends_jsonl_with_resource(self, tmp_path):
        with SpanExporter(str(tmp_path), service="svc") as ex:
            assert ex.export(self._record())
        lines = open(ex.path).read().splitlines()
        assert len(lines) == 1
        doc = json.loads(lines[0])
        assert doc["resource"]["service"] == "svc"
        assert doc["resource"]["pid"] == os.getpid()

    def test_byte_bound_drops_with_counter(self, tmp_path):
        # byte-bound enforcement happens on the WRITER side (export()
        # is an enqueue); close() drains, then the counters are exact
        drops = []
        ex = SpanExporter(
            str(tmp_path), max_bytes=200, on_drop=drops.append
        )
        try:
            for i in range(10):
                assert ex.export(self._record(i))  # accepted: queued
        finally:
            ex.close()
        n_written = len(open(ex.path).read().splitlines())
        assert 0 < n_written < 10
        assert ex.dropped == 10 - n_written
        assert set(drops) == {"bytes"}

    def test_rate_limit_drops_with_counter(self, tmp_path):
        clock = [0.0]
        ex = SpanExporter(
            str(tmp_path), max_per_s=2.0, clock=lambda: clock[0]
        )
        try:
            assert ex.export(self._record(0))
            assert ex.export(self._record(1))
            assert not ex.export(self._record(2))  # bucket empty
            clock[0] += 1.0  # refills 2 tokens
            assert ex.export(self._record(3))
        finally:
            ex.close()
        assert ex.dropped == 1

    def test_export_after_close_drops_never_raises(self, tmp_path):
        ex = SpanExporter(str(tmp_path))
        ex.close()
        ex.close()  # idempotent
        assert not ex.export(self._record())
        assert ex.dropped == 1

    def test_unencodable_record_drops(self, tmp_path):
        with SpanExporter(str(tmp_path)) as ex:
            ex.export({"spanId": object()})  # accepted; writer drops
        assert ex.dropped == 1

    def test_queue_bound_drops_at_enqueue(self, tmp_path):
        drops = []
        ex = SpanExporter(
            str(tmp_path), max_queue=2, on_drop=drops.append
        )
        # no writer races the queue check: stuff the queue before the
        # writer thread can drain by holding the condition
        with ex._cond:
            ex._queue.extend([self._record(0), self._record(1)])
        try:
            assert not ex.export(self._record(2))
        finally:
            ex.close()
        assert "queue" in drops

    def test_resolve_export_dir_rules(self, tmp_path, monkeypatch):
        monkeypatch.delenv("KOORD_TRACE_EXPORT", raising=False)
        assert resolve_export_dir(None) is None
        assert resolve_export_dir(False) is None
        assert resolve_export_dir(str(tmp_path)) == str(tmp_path)
        assert resolve_export_dir("1", state_dir="/sd") == "/sd/traces"
        assert resolve_export_dir("off") is None
        monkeypatch.setenv("KOORD_TRACE_EXPORT", str(tmp_path))
        assert resolve_export_dir(None) == str(tmp_path)
        # False must beat the env: the oracle/baseline opt-out
        assert resolve_export_dir(False) is None


def _span(trace, span, parent=None, name="x", kind="server",
          links=(), attrs=None, start=0):
    return {
        "traceId": trace, "spanId": span, "parentSpanId": parent,
        "name": name, "kind": kind,
        "startTimeUnixNano": start, "endTimeUnixNano": start + 1000,
        "durMs": 0.001, "status": {"code": "OK"},
        "attributes": dict(attrs or {}),
        "links": [
            {"traceId": lt, "spanId": ls, "type": LINK_FANIN}
            for lt, ls in links
        ],
    }


def _write_jsonl(path, spans):
    with open(path, "w") as fh:
        for s in spans:
            fh.write(json.dumps(s) + "\n")


class TestAssembler:
    def test_tree_and_cross_trace_links(self, tmp_path):
        # trace A: op -> attempt -> server -> launch; trace B's server
        # fan-in links to A's launch (the coalesced-batch shape)
        a, b = "a" * 32, "b" * 32
        _write_jsonl(tmp_path / "p1.jsonl", [
            _span(a, "op-a", name="score", kind="client"),
            _span(a, "att-a", "op-a", name="score.attempt",
                  kind="client", attrs={"server_span": "srv-a"}),
        ])
        _write_jsonl(tmp_path / "p2.jsonl", [
            _span(a, "srv-a", "att-a", name="score"),
            _span(a, "launch", "srv-a", name="score_launch",
                  kind="internal"),
            _span(b, "op-b", name="score", kind="client"),
            _span(b, "att-b", "op-b", kind="client",
                  attrs={"server_span": "srv-b"}),
            _span(b, "srv-b", "att-b", links=[(a, "launch")]),
        ])
        asm = assemble_mod.assemble([str(tmp_path)])
        assert set(asm.traces) == {a, b}
        assert not asm.orphan_spans
        assert not asm.client_orphans
        assert all(t.complete for t in asm.traces.values())
        tree_a = asm.traces[a]
        assert [s["spanId"] for s in tree_a.roots] == ["op-a"]
        assert [s["spanId"] for s in tree_a.children("srv-a")] == [
            "launch"
        ]

    def test_orphan_and_unresolved_flagged(self, tmp_path):
        t = "c" * 32
        _write_jsonl(tmp_path / "p.jsonl", [
            _span(t, "op", name="score", kind="client"),
            # parent never exported -> orphan
            _span(t, "lost", "ghost", kind="client"),
            # recorded server span nobody exported -> unresolved ref
            _span(t, "att", "op", kind="client",
                  attrs={"server_span": "missing"}),
        ])
        asm = assemble_mod.assemble([str(tmp_path)])
        tree = asm.traces[t]
        assert not tree.complete
        assert [s["spanId"] for s in tree.orphans] == ["lost"]
        assert [s["spanId"] for s in tree.unresolved] == ["att"]
        # both defects are client-kind: they count as client orphans
        assert {
            s["spanId"] for s in asm.client_orphans
        } == {"lost", "att"}

    def test_malformed_lines_counted_not_fatal(self, tmp_path):
        with open(tmp_path / "p.jsonl", "w") as fh:
            fh.write(json.dumps(_span("d" * 32, "s1")) + "\n")
            fh.write("{torn json line\n")
            fh.write(json.dumps({"no": "ids"}) + "\n")
        asm = assemble_mod.assemble([str(tmp_path)])
        assert asm.malformed_lines == 2
        assert len(asm.spans_by_id) == 1

    def test_waterfall_renders(self, tmp_path):
        t = "e" * 32
        _write_jsonl(tmp_path / "p.jsonl", [
            _span(t, "root", name="assign", kind="client"),
            _span(t, "child", "root", name="assign.attempt",
                  kind="client", start=200),
        ])
        asm = assemble_mod.assemble([str(tmp_path)])
        text = assemble_mod.render_waterfall(asm.traces[t])
        assert "assign [client]" in text
        assert "assign.attempt [client]" in text
        assert "INCOMPLETE" not in text

    def test_waterfall_device_split_renders(self, tmp_path):
        # device-time truth (ISSUE 19): a launch span carrying the
        # ledger's drained-note attrs splits its bar (# host, = device)
        # and annotates dev= / compile=; the header totals the sampled
        # device time across the trace
        t = "g" * 32
        launch = _span(t, "launch", "root", name="score_launch",
                       kind="internal")
        launch["durMs"] = 10.0
        launch["endTimeUnixNano"] = int(10.0 * 1e6)
        launch["attributes"] = {
            "device_us": 4000.0, "compiled": True,
            "compile_ms": 312.5, "flops": 1.5e9,
        }
        root = _span(t, "root", name="score")
        root["durMs"] = 12.0
        root["endTimeUnixNano"] = int(12.0 * 1e6)
        _write_jsonl(tmp_path / "p.jsonl", [root, launch])
        asm = assemble_mod.assemble([str(tmp_path)])
        text = assemble_mod.render_waterfall(asm.traces[t])
        assert "dev=4000.0us" in text
        assert "compile=312.50ms" in text
        # ~40% of the launch bar is the device share
        assert "=" in text and "#" in text
        launch_line = next(
            ln for ln in text.splitlines() if "score_launch" in ln
        )
        assert "=" in launch_line
        assert launch_line.index("#") < launch_line.index("=")
        assert "device 4.000 ms sampled across 1 span(s)" in text

    def test_waterfall_without_device_attrs_unchanged(self, tmp_path):
        # no ledger notes -> no device annotations anywhere (the
        # sample=0 rendering is byte-stable vs pre-ISSUE-19 traces)
        t = "h" * 32
        _write_jsonl(tmp_path / "p.jsonl", [
            _span(t, "root", name="score", kind="client"),
            _span(t, "child", "root", name="score_launch", start=200),
        ])
        asm = assemble_mod.assemble([str(tmp_path)])
        text = assemble_mod.render_waterfall(asm.traces[t])
        assert "dev=" not in text
        assert "compile=" not in text
        assert "device" not in text.splitlines()[0]
        assert "=" not in text

    def test_cli_check_exit_codes(self, tmp_path, capsys):
        t = "f" * 32
        _write_jsonl(tmp_path / "ok.jsonl", [_span(t, "s1")])
        assert assemble_mod.main([str(tmp_path), "--check"]) == 0
        _write_jsonl(
            tmp_path / "bad.jsonl", [_span(t, "s2", parent="ghost")]
        )
        assert assemble_mod.main([str(tmp_path), "--check"]) == 1
        out = capsys.readouterr().out
        assert "orphan" in out


@pytest.fixture(scope="module")
def traced_tier(tmp_path_factory):
    """One in-process traced tier: server + client over UDS gRPC, a
    short traced stream (sync, score, memo-hit score, assign, memo-hit
    assign), exports assembled once for the assertions below."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from koordinator_tpu.bridge.client import ScorerClient
    from koordinator_tpu.bridge.server import ScorerServicer, make_server
    from koordinator_tpu.harness.trace import ClusterModel, TraceConfig
    from koordinator_tpu.harness.trace import _build_init
    import numpy as np

    td = tmp_path_factory.mktemp("traced-tier")
    traces = str(td / "traces")
    sock = os.path.join(str(td), "s.sock")
    sv = ScorerServicer(trace_export=traces)
    server = make_server(servicer=sv)
    server.add_insecure_port(f"unix://{sock}")
    server.start()
    client = ScorerClient(f"unix://{sock}", trace_export=traces)
    rng = np.random.default_rng(5)
    cfg = TraceConfig(nodes=8, pod_slots=24, gangs=2, gang_min_member=2)
    model = ClusterModel(_build_init(cfg, rng))
    try:
        client.sync(
            node_allocatable=model.nalloc, node_requested=model.nreq,
            node_usage=model.nuse, metric_fresh=list(model.fresh),
            pod_requests=model.preq, pod_estimated=model.pest,
            priority=list(model.priority), gang_id=list(model.gang_id),
            quota_id=list(model.quota_id),
            gang_min_member=list(model.gang_min),
            quota_runtime=model.qrt, quota_used=model.quse,
            quota_limited=model.qlim,
        )
        client.score_flat(top_k=4)
        client.score_flat(top_k=4)  # memo hit
        client.assign()
        client.assign()  # assign memo hit
    finally:
        client.close()
        sv.telemetry.close()
        server.stop(0)
    return sv, assemble_mod.assemble([traces])


class TestEndToEnd:
    def test_five_rpcs_five_complete_trees(self, traced_tier):
        _sv, asm = traced_tier
        assert len(asm.traces) == 5
        assert not asm.orphan_spans
        assert not asm.client_orphans
        assert all(t.complete for t in asm.traces.values())

    def test_coalesced_launch_fan_in_and_memo_link(self, traced_tier):
        _sv, asm = traced_tier
        launches = [
            s for s in asm.spans_by_id.values()
            if s["name"] == "score_launch"
        ]
        assert len(launches) == 1  # the memo hit launched nothing
        launch = launches[0]
        # both score RPC spans — the launcher AND the memo hit — link
        # to the ONE launch span, across trace boundaries
        score_servers = [
            s for s in asm.spans_by_id.values()
            if s["name"] == "score" and s["kind"] == "server"
        ]
        assert len(score_servers) == 2
        for s in score_servers:
            assert any(
                link["spanId"] == launch["spanId"]
                for link in s["links"]
            ), s
        memo_hits = [
            s for s in score_servers
            if s["attributes"].get("memo_hit")
        ]
        assert len(memo_hits) == 1
        assert memo_hits[0]["traceId"] != launch["traceId"]

    def test_assign_memo_links_to_owner_span(self, traced_tier):
        _sv, asm = traced_tier
        assigns = [
            s for s in asm.spans_by_id.values()
            if s["name"] == "assign" and s["kind"] == "server"
        ]
        assert len(assigns) == 2
        memo = [s for s in assigns if s["attributes"].get("memo_hit")]
        owner = [
            s for s in assigns if not s["attributes"].get("memo_hit")
        ]
        assert len(memo) == 1 and len(owner) == 1
        assert memo[0]["links"][0]["spanId"] == owner[0]["spanId"]

    def test_server_span_echo_recorded_on_attempts(self, traced_tier):
        _sv, asm = traced_tier
        attempts = [
            s for s in asm.spans_by_id.values()
            if s["name"].endswith(".attempt")
        ]
        assert len(attempts) == 5
        for att in attempts:
            ref = att["attributes"]["server_span"]
            assert ref in asm.spans_by_id
            assert asm.spans_by_id[ref]["kind"] == "server"

    def test_span_families_counted(self, traced_tier):
        sv, _asm = traced_tier
        from koordinator_tpu.obs.scorer_metrics import TRACE_SPANS

        text = sv.telemetry.registry.render()
        assert 'koord_scorer_trace_spans_total{kind="server"}' in text
        assert 'kind="internal"' in text

    def test_assign_cycle_record_carries_trace_id(self, traced_tier):
        sv, asm = traced_tier
        from koordinator_tpu.obs import validate_flight_dump

        records = sv.telemetry.flight.snapshot()
        with_trace = [
            r for r in records if r.get("trace_id")
        ]
        assert with_trace, "no cycle record carries a trace_id"
        assert all(
            r["trace_id"] in asm.traces for r in with_trace
        )
        # the grown schema validates what the recorder writes
        doc = sv.telemetry.flight.document("test")
        assert validate_flight_dump(doc) == []


class TestFlightDumpTraceIdSchema:
    def _doc(self, trace_id):
        return {
            "version": 1, "reason": "test", "dumped_at_unix": 1.0,
            "config": {}, "dropped_cycles": 0,
            "cycles": [{
                "cycle_id": "c1", "snapshot_id": None,
                "trace_id": trace_id, "started_unix": 1.0,
                "spans": [], "notes": {}, "error": None,
            }],
        }

    def test_null_and_string_accepted(self):
        from koordinator_tpu.obs import validate_flight_dump

        assert validate_flight_dump(self._doc(None)) == []
        assert validate_flight_dump(self._doc("t" * 32)) == []

    def test_non_string_rejected(self):
        from koordinator_tpu.obs import validate_flight_dump

        problems = validate_flight_dump(self._doc(42))
        assert any("trace_id" in p for p in problems)
        problems = validate_flight_dump(self._doc(["t"]))
        assert any("trace_id" in p for p in problems)
