"""obs/slo.py: bucket-quantile estimation, declarative SLO verdicts,
and the delta-window quantiles /healthz serves (ISSUE 12).

The estimator edge cases here are the satellite's checklist: empty
family, all mass in one bucket, all mass in +Inf, a single
observation, and monotonicity across bucket boundaries — each one a
shape a production histogram actually takes (a quiet daemon, a
constant-latency stage, an outlier storm past the top bound)."""

import math

import pytest

from koordinator_tpu.koordlet.metrics import MetricsRegistry
from koordinator_tpu.obs.slo import (
    SloSpec,
    SloWindow,
    aggregate_buckets,
    evaluate_slos,
    histogram_quantile,
    quantile_from_buckets,
    slos_pass,
)

BOUNDS = (1.0, 5.0, 10.0, 50.0, float("inf"))


def _registry(family="f", buckets=BOUNDS):
    reg = MetricsRegistry()
    reg.register(family, "histogram", "test family", buckets=buckets)
    return reg


class TestQuantileFromBuckets:
    def test_empty_series_is_none(self):
        assert quantile_from_buckets(BOUNDS, (0, 0, 0, 0, 0), 0.99) is None
        assert quantile_from_buckets((), (), 0.5) is None
        # ragged input never guesses
        assert quantile_from_buckets(BOUNDS, (1, 1), 0.5) is None

    def test_all_mass_in_one_bucket_interpolates_inside_it(self):
        # 10 observations, all in (5, 10]: every quantile lands inside
        # that bucket's bounds
        cum = (0, 0, 10, 10, 10)
        for q in (0.01, 0.5, 0.99, 1.0):
            est = quantile_from_buckets(BOUNDS, cum, q)
            assert 5.0 <= est <= 10.0
        assert quantile_from_buckets(BOUNDS, cum, 1.0) == pytest.approx(10.0)
        assert quantile_from_buckets(BOUNDS, cum, 0.5) == pytest.approx(7.5)

    def test_all_mass_in_inf_bucket_reports_last_finite_bound(self):
        # the estimator must never invent a number above what the
        # buckets can support (the Prometheus convention)
        cum = (0, 0, 0, 0, 7)
        assert quantile_from_buckets(BOUNDS, cum, 0.5) == 50.0
        assert quantile_from_buckets(BOUNDS, cum, 0.99) == 50.0
        # degenerate: a lone +Inf bucket has no finite bound to report
        assert quantile_from_buckets(
            (float("inf"),), (3,), 0.5
        ) is None

    def test_single_observation(self):
        cum = (0, 1, 1, 1, 1)  # one observation in (1, 5]
        for q in (0.01, 0.5, 0.99):
            est = quantile_from_buckets(BOUNDS, cum, q)
            assert 1.0 <= est <= 5.0
        assert quantile_from_buckets(BOUNDS, cum, 1.0) == pytest.approx(5.0)

    def test_monotone_across_bucket_boundaries(self):
        # mass spread over every bucket incl. +Inf: estimates must be
        # non-decreasing as q sweeps, with no discontinuity at any
        # bucket boundary crossing
        cum = (4, 9, 15, 23, 25)
        prev = 0.0
        for i in range(1, 101):
            est = quantile_from_buckets(BOUNDS, cum, i / 100.0)
            assert est is not None and est >= prev - 1e-12
            prev = est
        assert prev == 50.0  # the top 2 observations live in +Inf

    def test_first_bucket_interpolates_from_zero(self):
        cum = (10, 10, 10, 10, 10)
        assert quantile_from_buckets(BOUNDS, cum, 0.5) == pytest.approx(0.5)
        assert quantile_from_buckets(BOUNDS, cum, 1.0) == pytest.approx(1.0)

    def test_q_is_clamped(self):
        cum = (0, 10, 10, 10, 10)
        assert quantile_from_buckets(BOUNDS, cum, -1.0) is not None
        assert quantile_from_buckets(BOUNDS, cum, 2.0) == pytest.approx(5.0)


class TestRegistryQuantiles:
    def test_label_subset_aggregation(self):
        reg = _registry()
        for band in ("prod", "batch"):
            for v in (2.0, 3.0):
                reg.histogram_observe(
                    "f", v, {"band": band, "rpc": "cycle"}
                )
        reg.histogram_observe("f", 40.0, {"band": "prod", "rpc": "sync"})
        # full-family aggregate sees all 5 observations
        bounds, cum, count = aggregate_buckets(reg, "f")
        assert count == 5
        # band subset sums both rpc series of that band
        _, _, prod_count = aggregate_buckets(reg, "f", {"band": "prod"})
        assert prod_count == 3
        # one exact series
        _, _, one = aggregate_buckets(
            reg, "f", {"band": "prod", "rpc": "sync"}
        )
        assert one == 1
        q = histogram_quantile(reg, "f", 0.99, {"band": "batch"})
        assert 1.0 <= q <= 5.0
        # unknown family/labels: None, never a guess
        assert histogram_quantile(reg, "nope", 0.5) is None
        assert histogram_quantile(reg, "f", 0.5, {"band": "zzz"}) is None

    def test_histogram_series_read_seam(self):
        reg = _registry()
        reg.histogram_observe("f", 7.0, {"k": "v"})
        series = reg.histogram_series("f")
        assert len(series) == 1
        labels, bounds, cum, total, count = series[0]
        assert labels == {"k": "v"}
        assert math.isinf(bounds[-1])
        assert cum[-1] == count == 1
        assert total == 7.0
        # non-histogram families return nothing
        reg.counter_add("c", 1)
        assert reg.histogram_series("c") == []


class TestSloSpecs:
    def test_pass_fail_and_no_data(self):
        reg = _registry()
        for v in (2.0, 2.0, 3.0):
            reg.histogram_observe("f", v, {"rpc": "score"})
        ok_spec = SloSpec("score-p99", "f", 0.99, threshold_ms=100.0,
                          labels={"rpc": "score"})
        tight = SloSpec("score-tight", "f", 0.99, threshold_ms=0.5,
                        labels={"rpc": "score"})
        blind = SloSpec("assign-p99", "f", 0.99, threshold_ms=100.0,
                        labels={"rpc": "assign"})
        verdicts = evaluate_slos(reg, [ok_spec, tight, blind])
        by_name = {v.spec.name: v for v in verdicts}
        assert by_name["score-p99"].ok
        assert not by_name["score-tight"].ok
        assert "threshold" in by_name["score-tight"].reason
        # a gate that cannot see is a FAILED gate, never silently green
        assert not by_name["assign-p99"].ok
        assert "no data" in by_name["assign-p99"].reason
        assert not slos_pass(verdicts)
        assert slos_pass([by_name["score-p99"]])
        assert not slos_pass([])  # an empty spec set judges nothing

    def test_min_count_gates_thin_windows(self):
        reg = _registry()
        reg.histogram_observe("f", 1.0)
        spec = SloSpec("p99", "f", 0.99, threshold_ms=100.0, min_count=10)
        (v,) = evaluate_slos(reg, [spec])
        assert not v.ok and "no data" in v.reason

    def test_verdict_doc_shape(self):
        reg = _registry()
        reg.histogram_observe("f", 2.0)
        (v,) = evaluate_slos(
            reg, [SloSpec("p50", "f", 0.5, threshold_ms=9.0)]
        )
        doc = v.to_doc()
        assert doc["name"] == "p50" and doc["ok"] is True
        assert doc["quantile"] == 0.5 and doc["threshold_ms"] == 9.0
        assert isinstance(doc["observed_ms"], float)
        assert doc["count"] == 1

    def test_labels_mapping_normalized(self):
        a = SloSpec("x", "f", 0.5, 1.0, labels={"b": "2", "a": "1"})
        assert a.labels == (("a", "1"), ("b", "2"))
        assert a.labels_dict() == {"a": "1", "b": "2"}


class TestSloWindow:
    def test_windows_are_deltas_not_cumulative(self):
        reg = _registry()
        reg.histogram_observe("f", 2.0, {"rpc": "score"})
        reg.histogram_observe("f", 2.0, {"rpc": "score"})
        win = SloWindow(families=("f",))
        first = win.advance(reg)["f"]["rpc=score"]
        assert first["count"] == 2
        assert 1.0 <= first["p99"] <= 5.0
        # a quiet window: count 0, null quantiles — visible, not
        # invented from stale cumulative mass
        second = win.advance(reg)["f"]["rpc=score"]
        assert second["count"] == 0
        assert second["p50"] is None and second["p99"] is None
        # new observations land in the NEXT window only
        reg.histogram_observe("f", 40.0, {"rpc": "score"})
        third = win.advance(reg)["f"]["rpc=score"]
        assert third["count"] == 1
        assert 10.0 <= third["p99"] <= 50.0

    def test_empty_family_renders_nothing(self):
        reg = _registry()
        assert SloWindow(families=("f", "ghost")).advance(reg) == {}
