"""Parity: the single-kernel Pallas cycle must be bit-identical with the
lax.scan reference path (solver/greedy.py) — same placements, same
post-cycle state — across strategies, gangs, quotas and padding shapes.

Runs in Pallas interpret mode on the CPU test platform; the compiled TPU
path is exercised by bench.py and __graft_entry__.py on hardware.
"""

import numpy as np
import pytest

from koordinator_tpu.config import CycleConfig
from koordinator_tpu.constraints import build_quota_table_inputs
from koordinator_tpu.harness import generators
from koordinator_tpu.model import encode_snapshot, resources as res
from koordinator_tpu.solver import greedy_assign
from koordinator_tpu.solver.pallas_cycle import greedy_assign_pallas as _wide
from koordinator_tpu.solver.pallas_dense import greedy_assign_dense as _dense


@pytest.fixture(params=["wide", "dense"])
def greedy_assign_pallas(request):
    """Both kernel layouts must hold the same bit-parity contract."""
    return _wide if request.param == "wide" else _dense


def _quota_snapshot(pods=48, nodes=16, **buckets):
    return generators.quota_colocation_snapshot(
        pods=pods, nodes=nodes, **buckets
    )[0]


def _assert_equal(scan, pallas):
    np.testing.assert_array_equal(
        np.asarray(scan.assignment), np.asarray(pallas.assignment)
    )
    np.testing.assert_array_equal(np.asarray(scan.status), np.asarray(pallas.status))
    np.testing.assert_array_equal(
        np.asarray(scan.node_requested), np.asarray(pallas.node_requested)
    )
    np.testing.assert_array_equal(
        np.asarray(scan.node_estimated), np.asarray(pallas.node_estimated)
    )
    np.testing.assert_array_equal(
        np.asarray(scan.quota_used), np.asarray(pallas.quota_used)
    )


class TestPallasCycleParity:
    def test_quota_colocation_default_cfg(self, greedy_assign_pallas):
        snap = _quota_snapshot()
        _assert_equal(greedy_assign(snap), greedy_assign_pallas(snap, interpret=True))

    def test_most_allocated_strategy(self, greedy_assign_pallas):
        snap = _quota_snapshot(pods=32, nodes=8)
        cfg = CycleConfig(fit_scoring_strategy="MostAllocated")
        _assert_equal(
            greedy_assign(snap, cfg), greedy_assign_pallas(snap, cfg, interpret=True)
        )

    def test_loadaware_disabled(self, greedy_assign_pallas):
        snap = _quota_snapshot(pods=32, nodes=8)
        cfg = CycleConfig(enable_loadaware=False)
        _assert_equal(
            greedy_assign(snap, cfg), greedy_assign_pallas(snap, cfg, interpret=True)
        )

    def test_gangs_and_overload(self, greedy_assign_pallas):
        nodes_l, pods_l, gangs = generators.loadaware_joint(seed=3, pods=40, nodes=6)[:3]
        snap = encode_snapshot(nodes_l, pods_l, gangs, [])
        _assert_equal(greedy_assign(snap), greedy_assign_pallas(snap, interpret=True))

    def test_unpadded_bucket_shapes(self, greedy_assign_pallas):
        # bucket sizes not multiples of 8/128 must still agree
        snap = _quota_snapshot(pods=21, nodes=5, node_bucket=5, pod_bucket=21)
        _assert_equal(greedy_assign(snap), greedy_assign_pallas(snap, interpret=True))

    def test_scarce_capacity_leaves_unscheduled(self, greedy_assign_pallas):
        nodes_l, pods_l, gangs = generators.loadaware_joint(seed=7, pods=64, nodes=2)[:3]
        snap = encode_snapshot(nodes_l, pods_l, gangs, [])
        scan = greedy_assign(snap)
        pallas = greedy_assign_pallas(snap, interpret=True)
        _assert_equal(scan, pallas)
        assert int((np.asarray(scan.assignment) < 0).sum()) > 0

    def test_extended_plugin_tensors(self, greedy_assign_pallas):
        """extra_mask/extra_scores ride the kernel as [N, P] tiles and stay
        bit-identical with the scan path carrying the same tensors."""
        import jax.numpy as jnp

        snap = _quota_snapshot(pods=40, nodes=12)
        P = snap.pods.capacity
        N = snap.nodes.allocatable.shape[0]
        rng = np.random.default_rng(11)
        extra_mask = jnp.asarray(rng.random((P, N)) > 0.25)
        extra_scores = jnp.asarray(rng.integers(0, 60, size=(P, N)), dtype=jnp.int64)
        want = greedy_assign(snap, extra_mask=extra_mask, extra_scores=extra_scores)
        got = greedy_assign_pallas(
            snap, interpret=True, extra_mask=extra_mask, extra_scores=extra_scores
        )
        _assert_equal(want, got)

    def test_extended_mask_only(self, greedy_assign_pallas):
        import jax.numpy as jnp

        snap = _quota_snapshot(pods=24, nodes=8)
        P = snap.pods.capacity
        N = snap.nodes.allocatable.shape[0]
        rng = np.random.default_rng(5)
        extra_mask = jnp.asarray(rng.random((P, N)) > 0.5)
        want = greedy_assign(snap, extra_mask=extra_mask)
        got = greedy_assign_pallas(snap, interpret=True, extra_mask=extra_mask)
        _assert_equal(want, got)


class TestPallasWaveParity:
    """cfg.wave > 1 swaps the wide kernel's per-pod fori_loop for the
    in-VMEM wave rounds (ISSUE 3): frozen top-M freeze, unpacked
    (score, index) certification, live in-wave Reserve.  Placements must
    stay bit-identical with the scan across knobs, strategies, quotas
    and extras, and the rounds output must show the batching."""

    def test_wave_knobs_parity_and_rounds(self):
        snap = _quota_snapshot(pods=48, nodes=16)
        for wave, top_m in ((8, 2), (32, 4)):
            cfg = CycleConfig(wave=wave, top_m=top_m)
            want = greedy_assign(snap, cfg)
            got = _wide(snap, cfg, interpret=True)
            _assert_equal(want, got)
            rounds = int(np.asarray(got.rounds))
            assert 1 <= rounds <= snap.pods.capacity

    def test_wave_most_allocated(self):
        """MostAllocated rides the refined closed universe in-kernel
        (own candidates + in-round committed nodes)."""
        snap = _quota_snapshot(pods=32, nodes=8)
        cfg = CycleConfig(fit_scoring_strategy="MostAllocated", wave=8,
                          top_m=4)
        _assert_equal(
            greedy_assign(snap, cfg), _wide(snap, cfg, interpret=True)
        )

    def test_wave_extended_plugin_tensors(self):
        import jax.numpy as jnp

        snap = _quota_snapshot(pods=40, nodes=12)
        P = snap.pods.capacity
        N = snap.nodes.allocatable.shape[0]
        rng = np.random.default_rng(17)
        extra_mask = jnp.asarray(rng.random((P, N)) > 0.25)
        extra_scores = jnp.asarray(
            rng.integers(0, 60, size=(P, N)), dtype=jnp.int64
        )
        cfg = CycleConfig(wave=8, top_m=2)
        want = greedy_assign(
            snap, cfg, extra_mask=extra_mask, extra_scores=extra_scores
        )
        got = _wide(
            snap, cfg, interpret=True,
            extra_mask=extra_mask, extra_scores=extra_scores,
        )
        _assert_equal(want, got)

    def test_wave_contention_degrades_to_single_commits(self):
        """Identical pods racing for one-pod-each nodes: candidates fill
        in-wave, uncertifiable pods end the prefix, and the kernel must
        place every pod exactly like the scan (the regression class
        TestWaveTightCapacity pins for the shard path)."""
        nodes_l = [
            {
                "name": f"tight-{i}",
                "allocatable": {"cpu": "1000m", "memory": 1 << 30,
                                "pods": 110},
            }
            for i in range(16)
        ]
        pods_l = [
            {
                "name": f"pod-{p}",
                "requests": {"cpu": "900m", "memory": 512 << 20, "pods": 1},
            }
            for p in range(12)
        ]
        snap = encode_snapshot(nodes_l, pods_l, [], [])
        cfg = CycleConfig(wave=8, top_m=2)
        want = greedy_assign(snap, cfg)
        got = _wide(snap, cfg, interpret=True)
        _assert_equal(want, got)
        assert int((np.asarray(got.assignment) >= 0).sum()) == 12

    def test_wave_gangs(self):
        nodes_l, pods_l, gangs = generators.loadaware_joint(
            seed=3, pods=40, nodes=6
        )[:3]
        snap = encode_snapshot(nodes_l, pods_l, gangs, [])
        cfg = CycleConfig(wave=8, top_m=4)
        _assert_equal(
            greedy_assign(snap, cfg), _wide(snap, cfg, interpret=True)
        )
