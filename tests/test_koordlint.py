"""koordlint under tier-1: the full static pass must hold at HEAD, and
every rule must catch its seeded PR-1 regression class.

``TestRepoIsClean`` is the enforcement seam — a violation anywhere in
the repo fails ``pytest tests/`` with the same file:line report the
``python -m koordinator_tpu.analysis`` CLI prints, zero new CI infra.
The seeded-regression tests feed synthetic sources through the same
code path the CLI uses (``run_rules_on_source`` / the wire-contract
text functions), so a rule that silently stops firing fails here too.
"""

import os
import re
import shutil
import subprocess
import textwrap

import pytest

from koordinator_tpu.analysis import RULES, wire_contract
from koordinator_tpu.analysis.core import (
    find_repo_root,
    parse_suppressions,
    run_repo,
    run_rules_on_source,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint(src: str, rules=None):
    return run_rules_on_source("fixture.py", textwrap.dedent(src), rules)


def read(*parts: str) -> str:
    with open(os.path.join(REPO, *parts), "r", encoding="utf-8") as f:
        return f.read()


class TestRepoIsClean:
    def test_full_pass_reports_zero_violations(self):
        violations = run_repo(root=REPO)
        assert violations == [], "\n" + "\n".join(
            v.format() for v in violations
        )

    def test_cli_exits_zero_on_repo(self):
        from koordinator_tpu.analysis.__main__ import main

        assert main(["--root", REPO]) == 0
        assert main(["--list-rules"]) == 0
        assert main(["--rules", "no-such-rule"]) == 2

    def test_cli_default_root_is_package_location_not_cwd(self, tmp_path,
                                                          monkeypatch):
        from koordinator_tpu.analysis.__main__ import main

        monkeypatch.chdir(tmp_path)
        assert main([]) == 0  # resolves the repo from the package, not cwd

    def test_rule_registry_matches_runner(self):
        # every advertised rule can be selected individually (empty
        # source + a wire-free repo pass: proves selection wiring
        # without five more full-repo scans)
        for rule in RULES:
            run_rules_on_source("f.py", "", [rule])
        run_repo(root=REPO, rules=list(RULES), wire=False)


class TestDonationSafety:
    FIXTURE = """
    import jax
    from functools import partial

    @partial(jax.jit, donate_argnums=(0,))
    def scatter(arr, idx, val):
        return arr.at[idx].set(val)

    def apply(buf, idx, val):
        out = scatter(buf, idx, val)
        check = buf.sum()
        return out, check
    """

    def test_read_after_donate_caught(self):
        got = lint(self.FIXTURE)
        assert [(v.rule, v.line) for v in got] == [("donation-safety", 11)]
        assert "donated to scatter()" in got[0].message

    def test_rebind_idiom_is_clean(self):
        assert lint("""
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def scatter(arr, idx, val):
            return arr.at[idx].set(val)

        def apply(buf, idx, val):
            buf = scatter(buf, idx, val)
            return buf.sum()
        """) == []

    def test_same_line_read_after_donate_caught(self):
        # the one-line form of the bug: the read sits on the call's line
        got = lint("""
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def scatter(arr, idx, val):
            return arr.at[idx].set(val)

        def apply(buf, idx, val):
            return scatter(buf, idx, val), buf.sum()
        """)
        assert [(v.rule, v.line) for v in got] == [("donation-safety", 10)]

    def test_read_before_donate_on_same_line_is_clean(self):
        # left-to-right evaluation: the read happens before the donation
        assert lint("""
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def scatter(arr, val):
            return arr + val

        def apply(buf, val):
            return buf.sum() + scatter(buf, val).sum()
        """) == []

    def test_augassign_is_a_read_not_a_forgiving_rebind(self):
        # `buf += 1` after donating buf READS the donated buffer — it is
        # a violation itself and must not silence the later read either
        got = lint("""
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def scatter(arr, idx, val):
            return arr.at[idx].set(val)

        def apply(buf, idx, val):
            out = scatter(buf, idx, val)
            buf += 1
            return out, buf
        """)
        assert [v.line for v in got] == [11, 12]
        assert all(v.rule == "donation-safety" for v in got)

    def test_jit_call_form_and_kwarg_donation(self):
        got = lint("""
        import jax

        def _inner(arr, val):
            return arr + val

        update = jax.jit(_inner, donate_argnums=(0,))

        def use(state, val):
            out = update(state, val)
            return out, state.mean()
        """)
        assert [(v.rule, v.line) for v in got] == [("donation-safety", 11)]

    def test_suppression_tag(self):
        src = self.FIXTURE.replace(
            "check = buf.sum()",
            "check = buf.sum()  # koordlint: disable=donation-safety(pre-donate copy held by caller)",
        )
        assert lint(src) == []

    def test_known_cross_module_donor_rescore_dirty_caught(self):
        # ISSUE 9: the resident-score-tensor scatter's jit wrapper lives
        # in solver/incremental.py, invisible to the module-local scan —
        # the known-donor table must still catch a read of the donated
        # scores buffer at a cross-module call site
        got = lint("""
        from koordinator_tpu.solver.incremental import rescore_dirty

        def advance(snap, scores, feasible, dirty, cfg):
            out_s, out_f = rescore_dirty(snap, scores, feasible, dirty, set(), cfg)
            stale = scores.sum()
            return out_s, out_f, stale
        """)
        assert [(v.rule, v.line) for v in got] == [("donation-safety", 6)]
        assert "donated to rescore_dirty()" in got[0].message

    def test_known_donor_rebind_and_non_donated_args_clean(self):
        # feasible is NOT donated (in-flight readbacks hold it): reading
        # it after the call is fine, and the rebind idiom forgives scores
        assert lint("""
        from koordinator_tpu.solver.incremental import rescore_dirty
        from koordinator_tpu.solver.resident import apply_flat_delta

        def advance(snap, scores, feasible, dirty, cfg):
            scores, feasible = rescore_dirty(
                snap, scores, feasible, dirty, set(), cfg)
            return scores, feasible.sum()

        def scatter(buf, idx, val):
            buf = apply_flat_delta(buf, idx, val)
            return buf
        """) == []

    def test_known_donor_apply_flat_delta_caught(self):
        got = lint("""
        from koordinator_tpu.solver.resident import apply_flat_delta

        def scatter(buf, idx, val):
            out = apply_flat_delta(buf, idx, val)
            return out, buf.sum()
        """)
        assert [(v.rule, v.line) for v in got] == [("donation-safety", 6)]

    def test_local_def_overrides_known_donor(self):
        # a module-LOCAL jitted def named rescore_dirty declares its own
        # (empty) donation contract; the cross-module table must not
        # impose the solver helper's on it
        assert lint("""
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("cfg",))
        def rescore_dirty(snapshot, scores, feasible, a, b, cfg):
            return scores, feasible

        def advance(snap, scores, feasible, cfg):
            out = rescore_dirty(snap, scores, feasible, 1, 2, cfg)
            return out, scores.sum()
        """) == []


class TestRetraceHazard:
    def test_tracer_branch_in_jitted_fixture(self):
        got = lint("""
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("cfg",))
        def cycle(x, n, cfg):
            if n > 3:
                x = x + 1
            assert n >= 0
            if cfg.flag:
                x = x * 2
            if x is not None:
                x = x - 1
            return x
        """)
        assert [(v.rule, v.line) for v in got] == [
            ("retrace-hazard", 7),
            ("retrace-hazard", 9),
        ]

    def test_shape_guards_are_clean(self):
        # shape/len branches are trace-time constants, not retraces
        assert lint("""
        import jax

        @jax.jit
        def f(x, y):
            if x.shape[0] > 3:
                x = x + 1
            assert len(x) == len(y)
            if x.ndim == 2 and x.size > 0:
                x = x * 2
            return x
        """) == []

    def test_static_and_is_none_branches_are_clean(self):
        # the repo's own idioms: branch on static cfg, on extras presence
        assert lint("""
        import jax, jax.numpy as jnp
        from functools import partial

        @partial(jax.jit, static_argnames=("cfg",))
        def cycle(x, extra, cfg):
            if cfg.enable:
                x = x + 1
            if extra is not None:
                x = x + extra
            return x
        """) == []

    def test_unhashable_and_str_tuple_static_args(self):
        got = lint("""
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("cfg", "names"))
        def f(x, cfg, names):
            return x

        def call(x):
            a = f(x, cfg=[1, 2], names=None)
            b = f(x, cfg=None, names=("pod-a", "pod-b"))
            return a, b
        """)
        msgs = [v.message for v in got]
        assert any("unhashable" in m for m in msgs)
        assert any("tuple-of-str" in m for m in msgs)

    def test_traced_wave_knobs_caught(self):
        """A jit boundary taking wave/top_m traced is a silent per-cycle
        retrace (the width selects loop structure); both decorator and
        call-form jit spellings must be caught, and the static spelling
        must pass (rule shape 4)."""
        got = lint("""
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("cfg",))
        def cycle(snapshot, cfg, wave, top_m):
            return snapshot

        def _inner(snapshot, wave):
            return snapshot

        batched = jax.jit(_inner)
        """)
        msgs = [(v.rule, v.message) for v in got]
        assert len(msgs) == 3
        assert all(r == "retrace-hazard" for r, _ in msgs)
        assert sum("'wave'" in m for _, m in msgs) == 2
        assert sum("'top_m'" in m for _, m in msgs) == 1
        assert all("static_argnames" in m for _, m in msgs)

    def test_static_wave_knobs_are_clean(self):
        assert lint("""
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("cfg", "wave", "top_m"))
        def cycle(snapshot, cfg, wave, top_m):
            return snapshot

        def _inner(snapshot, wave):
            return snapshot

        batched = jax.jit(_inner, static_argnames=("wave",))
        """) == []

    def test_traced_mesh_knobs_caught(self):
        """ISSUE 7: a jit boundary taking mesh/device-count/shard-width
        traced re-specializes the partitioned program per value — the
        same silent retrace class as the wave knobs; decorator and
        call-form spellings both caught."""
        got = lint("""
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("cfg",))
        def sharded_cycle(snapshot, cfg, mesh):
            return snapshot

        def _inner(arr, n_shards):
            return arr

        scatter = jax.jit(_inner)
        """)
        msgs = [(v.rule, v.message) for v in got]
        assert len(msgs) == 2
        assert all(r == "retrace-hazard" for r, _ in msgs)
        assert sum("'mesh'" in m for _, m in msgs) == 1
        assert sum("'n_shards'" in m for _, m in msgs) == 1
        assert all("static_argnames" in m for _, m in msgs)

    def test_static_mesh_knobs_are_clean(self):
        assert lint("""
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("cfg", "mesh"))
        def sharded_cycle(snapshot, cfg, mesh):
            return snapshot

        def _inner(arr, n_shards):
            return arr

        scatter = jax.jit(_inner, static_argnames=("n_shards",))
        """) == []

    def test_traced_dirty_knobs_caught(self):
        """ISSUE 9: a jit boundary taking a dirty COUNT traced is the
        same silent retrace class — delta sizes vary per cycle, so the
        rescore would re-specialize per distinct count; the count must
        ride a bucket-padded index vector instead.  Decorator and
        call-form spellings both."""
        got = lint("""
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("cfg",))
        def rescore(snapshot, scores, cfg, n_dirty):
            return scores

        def _inner(scores, dirty_width):
            return scores

        column_rescore = jax.jit(_inner)
        """)
        msgs = [(v.line, v.message) for v in got]
        assert len(msgs) == 2, msgs
        assert sum("'n_dirty'" in m for _, m in msgs) == 1
        assert sum("'dirty_width'" in m for _, m in msgs) == 1
        assert all("pad" in m for _, m in msgs)

    def test_static_or_padded_dirty_params_are_clean(self):
        # a padded index VECTOR (node_idx/pod_idx) carries no count at
        # the boundary; an explicitly-static count is also accepted
        assert lint("""
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("cfg",))
        def rescore(snapshot, scores, node_idx, pod_idx, cfg):
            return scores

        def _inner(scores, n_dirty):
            return scores

        sized = jax.jit(_inner, static_argnames=("n_dirty",))
        """) == []

    def test_traced_candidate_knobs_caught(self):
        """ISSUE 16: a jit boundary taking a candidate width/count
        traced is the same silent retrace class — the width is
        configuration (it rides the static CycleConfig) and per-pod
        feasible counts vary with every delta, so each distinct value
        re-specializes the sparse [P, C] program; decorator and
        call-form spellings both caught."""
        got = lint("""
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("cfg",))
        def sparse_score(snapshot, cand, cfg, num_candidates):
            return cand

        def _inner(cand, c_width):
            return cand

        gather = jax.jit(_inner)
        """)
        msgs = [(v.rule, v.message) for v in got]
        assert len(msgs) == 2, msgs
        assert all(r == "retrace-hazard" for r, _ in msgs)
        assert sum("'num_candidates'" in m for _, m in msgs) == 1
        assert sum("'c_width'" in m for _, m in msgs) == 1
        assert all(
            "pad the candidate list, don't trace the count" in m
            for _, m in msgs
        )

    def test_static_or_padded_candidate_params_are_clean(self):
        # the shipped spelling: C rides the static cfg and the list is
        # padded to C with sentinels — no count at any boundary; an
        # explicitly-static width is also accepted
        assert lint("""
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("cfg",))
        def sparse_score(snapshot, cand, count, cfg):
            return cand

        def _inner(cand, candidate_width):
            return cand

        sized = jax.jit(_inner, static_argnames=("candidate_width",))
        """) == []

    def test_mesh_knob_in_shard_map_body_caught(self):
        """A shard_map body taking a mesh knob as a PARAMETER receives
        it as a traced per-shard operand; the mesh belongs in the
        shard_map(..., mesh=) binding or the closure."""
        got = lint("""
        import jax
        from jax.sharding import PartitionSpec as P

        def run(arr, mesh):
            def body(a, num_shards):
                return a * num_shards

            return jax.shard_map(
                body, mesh=mesh, in_specs=(P("nodes"), P()),
                out_specs=P("nodes"),
            )(arr, mesh.size)
        """)
        assert len(got) == 1
        assert got[0].rule == "retrace-hazard"
        assert "'num_shards'" in got[0].message
        assert "shard_map" in got[0].message

    def test_shard_map_body_without_mesh_knobs_is_clean(self):
        assert lint("""
        import jax
        from jax.sharding import PartitionSpec as P

        def run(arr, mesh):
            def body(a, deltas):
                return a + deltas

            return jax.shard_map(
                body, mesh=mesh, in_specs=(P("nodes"), P()),
                out_specs=P("nodes"),
            )(arr, arr)
        """) == []

    def test_shard_map_body_resolution_is_lexically_scoped(self):
        """Two same-named nested defs in different functions must not
        cross-resolve: the clean shard_map body in run() resolves to
        run's own `body`, never to the unrelated `body(a, num_shards)`
        elsewhere in the file (a file-wide name table collided here)."""
        assert lint("""
        import jax
        from jax.sharding import PartitionSpec as P

        def run(arr, mesh):
            def body(a, deltas):
                return a + deltas

            return jax.shard_map(
                body, mesh=mesh, in_specs=(P("nodes"), P()),
                out_specs=P("nodes"),
            )(arr, arr)

        def unrelated():
            def body(a, num_shards):
                return a * num_shards

            return body
        """) == []

    def test_namey_pytree_metadata(self):
        got = lint("""
        import dataclasses
        import jax

        @dataclasses.dataclass
        class T:
            rows: object
            names: tuple = ()

        jax.tree_util.register_dataclass(
            T, data_fields=["rows"], meta_fields=["names"]
        )
        """)
        assert len(got) == 1 and "PR-1" in got[0].message


class TestTermConfigRule:
    """retrace-hazard shape 5 (ISSUE 15): CycleConfig term configs must
    be frozen/hashable, mappings through _freeze."""

    CLEAN = """
    import dataclasses

    def _freeze(m):
        return tuple(sorted(m.items())) if not isinstance(m, tuple) else m

    @dataclasses.dataclass(frozen=True)
    class PackingTermArgs:
        weight: int = 1
        headroom: ResMap = ()

        def __post_init__(self):
            object.__setattr__(self, "headroom", _freeze(self.headroom))

    @dataclasses.dataclass(frozen=True)
    class CycleConfig:
        packing: "PackingTermArgs | None" = None
        wave: int = 1
    """

    def test_compliant_term_config_is_clean(self):
        assert lint(self.CLEAN, rules=["retrace-hazard"]) == []

    def test_unfrozen_term_config_flagged(self):
        got = lint("""
        import dataclasses

        @dataclasses.dataclass
        class SensitivityTermArgs:
            weight: int = 1

        @dataclasses.dataclass(frozen=True)
        class CycleConfig:
            sensitivity: "SensitivityTermArgs | None" = None
        """, rules=["retrace-hazard"])
        assert [v.rule for v in got] == ["retrace-hazard"]
        assert "frozen=True" in got[0].message

    def test_unfrozen_mapping_field_flagged(self):
        got = lint("""
        import dataclasses

        @dataclasses.dataclass(frozen=True)
        class PackingTermArgs:
            headroom: ResMap = ()

        @dataclasses.dataclass(frozen=True)
        class CycleConfig:
            packing: "PackingTermArgs | None" = None
        """, rules=["retrace-hazard"])
        assert len(got) == 1
        assert "_freeze" in got[0].message

    def test_mutable_default_flagged(self):
        got = lint("""
        import dataclasses

        def _freeze(m):
            return tuple(m)

        @dataclasses.dataclass(frozen=True)
        class HetTermArgs:
            table: list = []

        @dataclasses.dataclass(frozen=True)
        class CycleConfig:
            heterogeneity: "HetTermArgs | None" = None
        """, rules=["retrace-hazard"])
        assert any("mutable" in v.message for v in got)

    def test_transitive_reach_through_nested_config(self):
        # a mapping two hops from CycleConfig is still checked
        got = lint("""
        import dataclasses

        @dataclasses.dataclass(frozen=True)
        class AggArgs:
            thresholds: ResMap = ()

        @dataclasses.dataclass(frozen=True)
        class LoadArgs:
            aggregated: "AggArgs | None" = None

        @dataclasses.dataclass(frozen=True)
        class CycleConfig:
            loadaware: LoadArgs = LoadArgs()
        """, rules=["retrace-hazard"])
        assert len(got) == 1 and "AggArgs.thresholds" in got[0].message

    def test_no_cycleconfig_means_no_checks(self):
        assert lint("""
        import dataclasses

        @dataclasses.dataclass
        class Whatever:
            stuff: dict = None
        """, rules=["retrace-hazard"]) == []


class TestHostSyncInJit:
    def test_all_four_sync_shapes(self):
        got = lint("""
        import jax, numpy as np, jax.numpy as jnp

        @jax.jit
        def f(x):
            y = np.asarray(x)
            z = x.item()
            w = int(jnp.max(x))
            print("debug", x)
            return y, z, w
        """)
        assert [v.line for v in got] == [6, 7, 8, 9]
        assert all(v.rule == "host-sync-in-jit" for v in got)

    def test_closure_scanned_once_nested_jit_not_double_reported(self):
        # a lax.scan step closure executes under the enclosing trace and
        # is scanned; a nested JITTED def is reported exactly once
        got = lint("""
        import jax, numpy as np

        @jax.jit
        def outer(x):
            def step(carry, v):
                bad = np.asarray(v)
                return carry, bad
            return jax.lax.scan(step, x, x)

        @jax.jit
        def parent(x):
            @jax.jit
            def inner(y):
                return np.asarray(y)
            return inner(x)
        """)
        assert [v.line for v in got] == [7, 15]  # once each, no doubles
        assert "outer" in got[0].message  # closure attributed to outer
        assert "inner" in got[1].message  # nested jit attributed to itself

    def test_host_side_int_is_clean(self):
        # int() on shapes/enums is a trace-time constant, not a sync
        assert lint("""
        import jax, jax.numpy as jnp

        @jax.jit
        def f(x, k):
            n = int(x.shape[0])
            m = int(SomeEnum.PROD)
            return x[:n] + m
        """) == []


class TestSpanLeak:
    """ISSUE 4: the obs span API must end every span on every exit."""

    def test_naked_begin_span_caught(self):
        got = lint("""
        def stage(recorder):
            h = recorder.begin_span("dispatch")
            do_work()
            recorder.end_span(h)
        """)
        assert [(v.rule, v.line) for v in got] == [("span-leak", 3)]
        assert "try/finally" in got[0].message

    def test_try_finally_form_is_clean(self):
        assert lint("""
        def stage(recorder):
            h = recorder.begin_span("dispatch")
            try:
                do_work()
            finally:
                recorder.end_span(h)
        """) == []

    def test_begin_inside_protected_try_is_clean(self):
        assert lint("""
        def stage(recorder):
            try:
                h = recorder.begin_span("x")
                work()
            finally:
                recorder.end_span(h)
        """) == []

    def test_begin_in_finally_is_not_protected_by_itself(self):
        # a begin inside the very finalbody that ends OTHER spans has no
        # guarantee of its own
        got = lint("""
        def stage(recorder):
            try:
                work()
            finally:
                h = recorder.begin_span("cleanup")
                recorder.end_span(other)
        """)
        assert [(v.rule, v.line) for v in got] == [("span-leak", 6)]

    def test_try_without_end_in_finally_caught(self):
        got = lint("""
        def stage(recorder):
            h = recorder.begin_span("dispatch")
            try:
                do_work()
            finally:
                log("done")
        """)
        assert [(v.rule, v.line) for v in got] == [("span-leak", 3)]

    def test_context_manager_implementation_is_clean(self):
        # obs/spans.py's own _SpanContext shape: begin in __enter__,
        # end in __exit__
        assert lint("""
        class Ctx:
            def __enter__(self):
                self.h = self.rec.begin_span(self.name)
                return self

            def __exit__(self, *exc):
                self.rec.end_span(self.h)
        """) == []

    def test_enter_without_matching_exit_caught(self):
        got = lint("""
        class Ctx:
            def __enter__(self):
                self.h = self.rec.begin_span(self.name)
                return self

            def __exit__(self, *exc):
                pass
        """)
        assert [(v.rule, v.line) for v in got] == [("span-leak", 4)]

    def test_suppression_tag(self):
        assert lint("""
        def stage(recorder):
            h = recorder.begin_span("dispatch")  # koordlint: disable=span-leak(caller owns the end)
            return h
        """) == []


class TestSpanLeakTraceAPI:
    """ISSUE 14: the rule extends over the distributed-tracing surface
    — a ``start_trace_span`` must end/abort on all exits (a leaked
    TraceSpan never exports, so the assembled tree silently loses the
    RPC) and a ``SpanExporter`` handle must be closed."""

    def test_leaked_trace_span_caught(self):
        got = lint("""
        def rpc(spans, trace_id):
            span = spans.start_trace_span("score", trace_id)
            reply = serve()
            span.end()
            return reply
        """)
        assert [(v.rule, v.line) for v in got] == [("span-leak", 3)]
        assert "TraceSpan" in got[0].message

    def test_abort_in_handler_plus_end_is_clean(self):
        # the server wrapper shape (bridge/server.py sync/score/assign)
        assert lint("""
        def rpc(spans, trace_id):
            span = spans.start_trace_span("score", trace_id)
            try:
                reply = serve()
            except BaseException as exc:
                span.abort(exc)
                raise
            span.end()
            return reply
        """) == []

    def test_end_in_finally_is_clean(self):
        assert lint("""
        def rpc(spans, trace_id):
            span = spans.start_trace_span("score", trace_id)
            try:
                return serve()
            finally:
                span.end()
        """) == []

    def test_with_block_is_clean(self):
        assert lint("""
        def rpc(spans, trace_id):
            with spans.start_trace_span("score", trace_id) as span:
                return serve(span)
        """) == []

    def test_factory_return_is_clean(self):
        # ScorerServicer._start_rpc_span: ownership moves to the caller
        assert lint("""
        def _start_rpc_span(self, name, req):
            return self.spans.start_trace_span(name, req.trace_id)
        """) == []

    def test_end_without_error_path_caught(self):
        # an end() with no abort/finally anywhere: the exception path
        # leaks the span
        got = lint("""
        def rpc(spans, trace_id):
            span = spans.start_trace_span("score", trace_id)
            reply = serve()
            if reply.ok:
                span.end()
            return reply
        """)
        assert [(v.rule, v.line) for v in got] == [("span-leak", 3)]

    def test_unclosed_exporter_caught(self):
        got = lint("""
        def export_all(records, path):
            exporter = SpanExporter(path)
            for record in records:
                exporter.export(record)
        """)
        assert [(v.rule, v.line) for v in got] == [("span-leak", 3)]
        assert "closed" in got[0].message

    def test_exporter_with_block_is_clean(self):
        assert lint("""
        def export_all(records, path):
            with SpanExporter(path) as exporter:
                for record in records:
                    exporter.export(record)
        """) == []

    def test_exporter_close_in_finally_is_clean(self):
        assert lint("""
        def export_all(records, path):
            exporter = SpanExporter(path)
            try:
                for record in records:
                    exporter.export(record)
            finally:
                exporter.close()
        """) == []

    def test_exporter_held_on_self_with_close_is_clean(self):
        # the CycleTelemetry / ScorerClient lifetime shape
        assert lint("""
        class Telemetry:
            def __init__(self, path):
                self.exporter = SpanExporter(path)

            def close(self):
                self.exporter.close()
        """) == []

    def test_exporter_on_self_without_close_method_caught(self):
        got = lint("""
        class Telemetry:
            def __init__(self, path):
                self.exporter = SpanExporter(path)
        """)
        assert [(v.rule, v.line) for v in got] == [("span-leak", 4)]

    def test_trace_span_suppression_tag(self):
        assert lint("""
        def launch(spans, trace_id):
            span = spans.start_trace_span("launch", trace_id)  # koordlint: disable=span-leak(ends in the readback closure)
            return span
        """) == []


class TestHostSyncObsAPI:
    """The obs API inside jitted code is the print() trap plus a
    potential tracer concretization — the host-sync rule covers it."""

    def test_obs_calls_in_jit_caught(self):
        got = lint("""
        import jax

        @jax.jit
        def cycle(x, spans):
            h = spans.begin_span("inner")
            try:
                y = x + 1
            finally:
                spans.end_span(h)
            with spans.span("scale"):
                y = y * 2
            spans.note("rounds", y)
            return y
        """)
        assert [v.line for v in got] == [6, 10, 11, 13]
        assert all(v.rule == "host-sync-in-jit" for v in got)
        assert all("obs span API" in v.message for v in got)

    def test_telemetry_receiver_chain_caught(self):
        got = lint("""
        import jax

        @jax.jit
        def cycle(x, self):
            self.telemetry.spans.note("path", "scan")
            return x
        """)
        assert [(v.rule, v.line) for v in got] == [("host-sync-in-jit", 6)]

    def test_obs_outside_jit_is_clean(self):
        assert lint("""
        def serve(recorder, snap):
            with recorder.span("dispatch"):
                result = run_cycle(snap)
            recorder.note("path", result.path)
            return result
        """) == []

    def test_unrelated_span_named_method_is_clean(self):
        # .span()/.note() only count on a telemetry-ish receiver
        assert lint("""
        import jax

        @jax.jit
        def f(x, tree):
            y = tree.span(x)
            tree.note(y)
            return y
        """) == []


class TestLockHeldDispatch:
    """ISSUE 5: the serialized-daemon bug class — a blocking device
    readback performed while the servicer state lock is held serializes
    every RPC behind one transfer.  The coalescing refactor's invariant
    is lexical (capture under the lock, read back outside), so the rule
    checks exactly that."""

    def test_readbacks_under_state_lock_caught(self):
        out = lint("""
        import numpy as np
        import jax

        class Servicer:
            def score(self):
                with self._state_lock:
                    snap = self.state.snapshot()
                    scores = np.asarray(snap.scores)
                    n = snap.rounds.item()
                    snap.result.block_until_ready()
                    a, b = jax.device_get((snap.a, snap.b))
                return scores
        """, ["lock-held-dispatch"])
        assert len(out) == 4
        assert all(v.rule == "lock-held-dispatch" for v in out)

    def test_pre_split_servicer_spelling_caught(self):
        # the pre-refactor servicer held a bare self._lock across the
        # readback; the rule must catch that spelling too
        out = lint("""
        import numpy as np

        class Servicer:
            def assign(self):
                with self._lock:
                    assignment = np.asarray(self.result.assignment)
                return assignment
        """, ["lock-held-dispatch"])
        assert [v.line for v in out] == [7]

    def test_capture_then_readback_outside_is_clean(self):
        out = lint("""
        import numpy as np
        import jax

        class Servicer:
            def score(self):
                with self._state_lock:
                    snap = self.state.snapshot()
                    sid = self.snapshot_id()
                scores = np.asarray(snap.scores)
                a, b = jax.device_get((snap.a, snap.b))
                return sid, scores
        """, ["lock-held-dispatch"])
        assert out == []

    def test_closure_defined_under_lock_is_clean(self):
        # a closure DEFINED under the lock runs elsewhere (the device
        # section hands it to the dispatch queue) — not a violation
        out = lint("""
        import numpy as np

        class Servicer:
            def assign(self):
                with self._state_lock:
                    def launch():
                        return np.asarray(self.result.assignment)
                return self.dispatch.run_exclusive(launch)
        """, ["lock-held-dispatch"])
        assert out == []

    def test_unrelated_lock_names_are_clean(self):
        out = lint("""
        import numpy as np

        def save(self):
            with self._PALLAS_LOCK:
                return np.asarray(self.table)
        """, ["lock-held-dispatch"])
        assert out == []

    def test_suppression_tag(self):
        out = lint("""
        import numpy as np

        def save(self):
            with self._lock:
                return np.array(self.index)  # koordlint: disable=lock-held-dispatch
        """, ["lock-held-dispatch"])
        assert out == []

    # -- ISSUE 6: the pipeline seam (launch sections must not block) --
    def test_blocking_readback_in_launch_section_caught(self):
        out = lint("""
        import jax
        from koordinator_tpu.bridge.coalesce import launch_section

        class Servicer:
            @launch_section
            def _score_launch_batch(self, batch):
                scores = self.compute(batch)
                a, b = jax.device_get((scores.a, scores.b))
                scores.result.block_until_ready()
                return None
        """, ["lock-held-dispatch"])
        assert len(out) == 2
        assert all("launch critical section" in v.message for v in out)

    def test_attribute_decorator_form_caught(self):
        out = lint("""
        import numpy as np
        from koordinator_tpu.bridge import coalesce

        @coalesce.launch_section
        def launch(snap):
            return np.asarray(snap.scores)
        """, ["lock-held-dispatch"])
        assert [v.line for v in out] == [7]

    def test_readback_closure_inside_launch_section_is_clean(self):
        # the nested def IS the readback closure — the only code
        # allowed to block, run by the dispatcher off the launch lock
        out = lint("""
        import jax
        from koordinator_tpu.bridge.coalesce import launch_section

        class Servicer:
            @launch_section
            def _score_launch_batch(self, batch):
                scores = self.compute(batch)

                def _readback():
                    return jax.device_get(scores)

                return _readback
        """, ["lock-held-dispatch"])
        assert out == []

    def test_launch_lock_with_block_caught(self):
        out = lint("""
        import numpy as np

        class Dispatcher:
            def lead(self):
                with self._launch_lock:
                    return np.asarray(self.pending)
        """, ["lock-held-dispatch"])
        assert [v.line for v in out] == [7]
        assert "launch critical section" in out[0].message

    def test_undecorated_launch_helper_not_flagged(self):
        # lexical rule: only the decorator (or the lock) marks launch
        # code; a plain helper named "launch" stays out of scope
        out = lint("""
        import numpy as np

        def launch(snap):
            return np.asarray(snap.scores)
        """, ["lock-held-dispatch"])
        assert out == []


class TestBareRetry:
    """ISSUE 11: retry loops with a fixed ``time.sleep`` and no
    jitter/cap/deadline fail lint; computed (policy-paced) delays and
    poll loops without failure-eating handlers stay clean."""

    def test_fixed_sleep_retry_loop_caught(self):
        out = lint(
            """
            import time

            def redial(path):
                while True:
                    try:
                        connect(path)
                        return
                    except OSError:
                        pass
                    time.sleep(0.05)
            """,
            ["bare-retry"],
        )
        assert len(out) == 1
        assert out[0].rule == "bare-retry"
        assert "BackoffPolicy" in out[0].message

    def test_bare_sleep_import_form_caught(self):
        out = lint(
            """
            from time import sleep

            def poll():
                for attempt in range(5):
                    try:
                        return fetch()
                    except ValueError:
                        sleep(2)
            """,
            ["bare-retry"],
        )
        assert len(out) == 1

    def test_policy_paced_delay_is_clean(self):
        out = lint(
            """
            import time

            def redial(path, backoff):
                attempt = 0
                while True:
                    try:
                        connect(path)
                        return
                    except OSError:
                        pass
                    time.sleep(backoff.delay_ms(attempt) / 1000.0)
                    attempt += 1
            """,
            ["bare-retry"],
        )
        assert out == []

    def test_poll_loop_without_except_is_clean(self):
        # a liveness/status poll retries nothing — no handler in the
        # loop, no violation (bench's ppid watch is this shape)
        out = lint(
            """
            import time

            def watch(ppid):
                while alive(ppid):
                    time.sleep(0.5)
            """,
            ["bare-retry"],
        )
        assert out == []

    def test_except_outside_loop_is_clean(self):
        out = lint(
            """
            import time

            def watch(ppid):
                try:
                    while alive(ppid):
                        time.sleep(0.5)
                except KeyboardInterrupt:
                    pass
            """,
            ["bare-retry"],
        )
        assert out == []

    def test_nested_loops_report_once(self):
        out = lint(
            """
            import time

            def drain(items):
                while True:
                    for it in items:
                        try:
                            push(it)
                        except OSError:
                            time.sleep(1)
            """,
            ["bare-retry"],
        )
        assert len(out) == 1

    def test_suppression_tag(self):
        out = lint(
            """
            import time

            def watch(path):
                while True:
                    try:
                        check(path)
                    except OSError:
                        pass
                    time.sleep(0.5)  # koordlint: disable=bare-retry(fixed-cadence status poll, not a retry)
            """,
            ["bare-retry"],
        )
        assert out == []


class TestBroadExcept:
    def test_silent_swallow_caught_and_tag_respected(self):
        got = lint("""
        def g():
            try:
                risky()
            except Exception:
                pass
        """)
        assert [(v.rule, v.line) for v in got] == [("broad-except", 5)]
        assert lint("""
        def g():
            try:
                risky()
            except Exception:  # koordlint: disable=broad-except(probe may be down)
                pass
        """) == []

    def test_surfacing_handlers_pass(self):
        assert lint("""
        import logging

        def g():
            try:
                risky()
            except Exception:
                raise
            try:
                risky()
            except Exception:
                logging.getLogger(__name__).exception("boom")
            try:
                risky()
            except Exception as exc:
                return {"error": str(exc)}
        """) == []


class TestMetricsDocDrift:
    """ISSUE 12: the koord_scorer_* family table in OBSERVABILITY.md is
    the operator contract — one-sided drift against the families
    registered in obs/scorer_metrics.py must fail lint in BOTH
    directions, kinds included (the wire-contract shape applied to
    observability)."""

    PY_FIXTURE = textwrap.dedent('''
        CYCLE_LATENCY = "koord_scorer_cycle_latency_ms"
        SHED_TOTAL = "koord_scorer_shed_total"

        _FAMILIES = (
            (CYCLE_LATENCY, "histogram", "cycle latency"),
            (SHED_TOTAL, "counter", "shed reads"),
            ("koord_scorer_replica_lag_ms", "gauge", "inline literal"),
        )
    ''')
    MD_FIXTURE = textwrap.dedent("""
        # Observability

        | family | kind | labels | meaning |
        |---|---|---|---|
        | `koord_scorer_cycle_latency_ms` | histogram | `path` | latency |
        | `koord_scorer_shed_total` | counter | `method` | shed reads |
        | `koord_scorer_replica_lag_ms` | gauge | — | follower lag |
    """)

    def test_aligned_sources_are_clean(self):
        from koordinator_tpu.analysis import metricsdoc

        assert metricsdoc.diff_metrics_doc(
            self.PY_FIXTURE, self.MD_FIXTURE
        ) == []

    def test_head_is_clean(self):
        from koordinator_tpu.analysis import metricsdoc

        root = find_repo_root(REPO)
        assert metricsdoc.check_repo(root) == []

    def test_registered_but_undocumented_caught(self):
        from koordinator_tpu.analysis import metricsdoc

        bad_md = self.MD_FIXTURE.replace(
            "| `koord_scorer_shed_total` | counter | `method` | shed reads |\n",
            "",
        )
        got = metricsdoc.diff_metrics_doc(self.PY_FIXTURE, bad_md)
        assert len(got) == 1
        assert got[0].rule == "metrics-doc-drift"
        assert "koord_scorer_shed_total" in got[0].message
        assert "missing" in got[0].message
        # flags the _FAMILIES entry's line in the PY source
        assert got[0].path.endswith("scorer_metrics.py")
        assert got[0].line > 0

    def test_documented_but_unregistered_caught(self):
        from koordinator_tpu.analysis import metricsdoc

        bad_py = self.PY_FIXTURE.replace(
            '    (SHED_TOTAL, "counter", "shed reads"),\n', ""
        )
        got = metricsdoc.diff_metrics_doc(bad_py, self.MD_FIXTURE)
        assert len(got) == 1
        assert "never registered" in got[0].message
        # flags the doc row's line
        assert got[0].path.endswith("OBSERVABILITY.md")
        assert got[0].line > 0

    def test_kind_mismatch_caught(self):
        from koordinator_tpu.analysis import metricsdoc

        bad_md = self.MD_FIXTURE.replace(
            "| `koord_scorer_replica_lag_ms` | gauge |",
            "| `koord_scorer_replica_lag_ms` | counter |",
        )
        got = metricsdoc.diff_metrics_doc(self.PY_FIXTURE, bad_md)
        assert any(
            "documented as 'counter'" in v.message
            and "registered as 'gauge'" in v.message
            for v in got
        )

    def test_unknown_documented_kind_caught(self):
        from koordinator_tpu.analysis import metricsdoc

        bad_md = self.MD_FIXTURE.replace(
            "| `koord_scorer_shed_total` | counter |",
            "| `koord_scorer_shed_total` | summary |",
        )
        got = metricsdoc.diff_metrics_doc(self.PY_FIXTURE, bad_md)
        assert any("unknown kind 'summary'" in v.message for v in got)

    def test_vanished_tables_fail_loudly(self):
        from koordinator_tpu.analysis import metricsdoc

        # a refactor that moves either table must update the parser,
        # not silently disable the rule
        got = metricsdoc.diff_metrics_doc("X = 1\n", self.MD_FIXTURE)
        assert any("no _FAMILIES entries" in v.message for v in got)
        got = metricsdoc.diff_metrics_doc(self.PY_FIXTURE, "# no table\n")
        assert any("no koord_scorer_* rows" in v.message for v in got)

    def test_rule_is_registered_and_runs_in_run_repo(self):
        assert "metrics-doc-drift" in RULES
        # rules-filtered run executes only this rule and stays clean
        assert run_repo(root=REPO, rules=["metrics-doc-drift"]) == []


class TestWireContract:
    """Seeded one-sided edits to a wire.go fixture must each fail."""

    @pytest.fixture(scope="class")
    def sources(self):
        return {
            "proto": read("koordinator_tpu", "bridge", "scorer.proto"),
            "wire": read("go", "scorerclient", "wire.go"),
            "delta": read("go", "scorerclient", "delta.go"),
            "state": read("koordinator_tpu", "bridge", "state.py"),
            "codec": read("koordinator_tpu", "replication", "codec.py"),
            "wirecheck": read("koordinator_tpu", "bridge",
                              "wirecheck.py"),
            "replica": read("go", "scorerclient", "replica.go"),
        }

    def test_head_is_clean(self, sources):
        assert wire_contract.diff_proto_go(sources["proto"], sources["wire"]) == []
        assert wire_contract.check_delta_constants(
            sources["delta"], sources["state"]
        ) == []
        assert wire_contract.check_pb2_descriptor(sources["proto"]) == []

    def _edit(self, text, old, new):
        assert old in text
        return text.replace(old, new)

    def test_reordered_field_caught(self, sources):
        bad = self._edit(
            sources["wire"],
            "\tb = appendPackedInt64(b, 4, p.Priority)\n"
            "\tb = appendPackedInt32(b, 5, p.GangID)",
            "\tb = appendPackedInt32(b, 5, p.GangID)\n"
            "\tb = appendPackedInt64(b, 4, p.Priority)",
        )
        got = wire_contract.diff_proto_go(sources["proto"], bad)
        assert any("ascending" in v.message for v in got)

    def test_renumbered_field_caught(self, sources):
        bad = self._edit(
            sources["wire"],
            "b = appendPackedInt32(b, 5, p.GangID)",
            "b = appendPackedInt32(b, 6, p.GangID)",
        )
        msgs = [v.message for v in
                wire_contract.diff_proto_go(sources["proto"], bad)]
        assert any("proto field 6 is 'quota_id'" in m for m in msgs)
        assert any("never emits proto field 5" in m for m in msgs)

    def test_wrong_width_caught(self, sources):
        bad = self._edit(
            sources["wire"],
            "b = appendPackedInt64(b, 4, p.Priority)",
            "b = appendPackedInt32(b, 4, p.Priority)",
        )
        got = wire_contract.diff_proto_go(sources["proto"], bad)
        assert any("expects appendPackedInt64" in v.message for v in got)

    def test_wrong_endianness_width_caught(self, sources):
        bad = self._edit(
            sources["wire"],
            "r.Flat.Score = leInt64s(g.val)",
            "r.Flat.Score = leInt32s(g.val)",
        )
        got = wire_contract.diff_proto_go(sources["proto"], bad)
        assert any("i64 LE" in v.message for v in got)

    def test_guard_does_not_leak_to_later_local_emits(self):
        """A consumed/closed `if r.X {` guard must not be attributed to a
        later emit staged through a local variable."""
        from koordinator_tpu.analysis.wire_contract import parse_go_marshals

        src = (
            "func (r *Msg) Marshal() []byte {\n"
            "\tvar b []byte\n"
            "\tif r.Flat {\n"
            "\t\tb = appendVarintField(b, 3, 1)\n"
            "\t}\n"
            "\tstaged := r.Payload\n"
            "\tb = appendBytesField(b, 4, staged)\n"
            "\treturn b\n"
            "}\n"
        )
        emits = parse_go_marshals(src)["Msg"]
        assert [(e.num, e.field) for e in emits] == [(3, "Flat"), (4, None)]

    def test_dropped_reply_field_caught(self, sources):
        bad = self._edit(
            sources["wire"],
            "\t\tcase 2:\n\t\t\tr.Nodes = int64(f.u)\n",
            "",
        )
        got = wire_contract.diff_proto_go(sources["proto"], bad)
        assert any(
            "UnmarshalSyncReply never decodes proto field 2" in v.message
            for v in got
        )

    def test_delta_ratio_drift_caught(self, sources):
        bad = self._edit(
            sources["delta"],
            "DefaultMaxDeltaRatio = 0.25",
            "DefaultMaxDeltaRatio = 0.5",
        )
        got = wire_contract.check_delta_constants(bad, sources["state"])
        assert any("disagree" in v.message for v in got)

    def test_delta_endianness_helper_caught(self, sources):
        bad = self._edit(
            sources["delta"],
            "t.DeltaIdx = LEInt64Bytes(idx)",
            "t.DeltaIdx = beInt64Bytes(idx)",
        )
        got = wire_contract.check_delta_constants(bad, sources["state"])
        assert any("DeltaIdx" in v.message for v in got)

    def test_go_line_suppression_honored(self, sources, tmp_path):
        """A reasoned deviation in wire.go is suppressible with a Go
        comment on the flagged line, through the same run_repo filter
        tier-1 uses."""
        import shutil

        from koordinator_tpu.analysis.core import run_repo

        root = tmp_path / "repo"
        (root / "koordinator_tpu" / "bridge").mkdir(parents=True)
        (root / "go" / "scorerclient").mkdir(parents=True)
        shutil.copy(
            os.path.join(REPO, "koordinator_tpu", "bridge", "scorer.proto"),
            root / "koordinator_tpu" / "bridge" / "scorer.proto",
        )
        bad = self._edit(
            sources["wire"],
            "b = appendPackedInt64(b, 4, p.Priority)",
            "b = appendPackedInt32(b, 4, p.Priority)",
        )
        (root / "go" / "scorerclient" / "wire.go").write_text(bad)
        got = run_repo(root=str(root), rules=["wire-contract"])
        assert any("appendPackedInt64" in v.message for v in got)
        tagged = bad.replace(
            "b = appendPackedInt32(b, 4, p.Priority)",
            "b = appendPackedInt32(b, 4, p.Priority) "
            "// koordlint: disable=wire-contract(fixture)",
        )
        (root / "go" / "scorerclient" / "wire.go").write_text(tagged)
        got = run_repo(root=str(root), rules=["wire-contract"])
        assert not any("appendPackedInt64" in v.message for v in got)

    # -- replication stream framing (ISSUE 8): the three statements of
    #    the frame header must agree, and every seeded one-sided edit
    #    must fail lint, scorer.proto-style --
    def test_replication_framing_head_is_clean(self, sources):
        assert wire_contract.check_replication_framing(
            sources["codec"], sources["wirecheck"], sources["replica"]
        ) == []

    def test_replication_go_width_drift_caught(self, sources):
        bad = sources["replica"].replace(
            '{"generation", 8},', '{"generation", 4},'
        )
        assert bad != sources["replica"]
        got = wire_contract.check_replication_framing(
            sources["codec"], sources["wirecheck"], bad
        )
        assert any("replicaFrameFields" in v.message for v in got)

    def test_replication_go_field_order_drift_caught(self, sources):
        bad = sources["replica"].replace(
            '{"epoch", 8},\n\t{"generation", 8},',
            '{"generation", 8},\n\t{"epoch", 8},',
        )
        assert bad != sources["replica"]
        got = wire_contract.check_replication_framing(
            sources["codec"], sources["wirecheck"], bad
        )
        assert any("disagrees" in v.message for v in got)

    def test_replication_magic_and_version_drift_caught(self, sources):
        bad = sources["replica"].replace(
            "ReplicaFrameMagic   = 0x4B52504C",
            "ReplicaFrameMagic   = 0x4B52504D",
        )
        assert bad != sources["replica"]
        got = wire_contract.check_replication_framing(
            sources["codec"], sources["wirecheck"], bad
        )
        assert any("MAGIC" in v.message for v in got)
        bad = sources["replica"].replace(
            "ReplicaFrameVersion = 1", "ReplicaFrameVersion = 2"
        )
        got = wire_contract.check_replication_framing(
            sources["codec"], sources["wirecheck"], bad
        )
        assert any("VERSION" in v.message for v in got)

    def test_replication_header_len_drift_caught(self, sources):
        bad = sources["replica"].replace(
            "ReplicaHeaderLen    = 34", "ReplicaHeaderLen    = 30"
        )
        assert bad != sources["replica"]
        got = wire_contract.check_replication_framing(
            sources["codec"], sources["wirecheck"], bad
        )
        assert any("ReplicaHeaderLen" in v.message for v in got)

    def test_replication_wirecheck_mirror_drift_caught(self, sources):
        bad = sources["wirecheck"].replace(
            '("stamp_us", 8),', '("stamp_us", 4),'
        )
        assert bad != sources["wirecheck"]
        got = wire_contract.check_replication_framing(
            sources["codec"], bad, sources["replica"]
        )
        assert any("REPLICA_FRAME_FIELDS" in v.message for v in got)

    def test_replication_missing_tables_flagged(self, sources):
        got = wire_contract.check_replication_framing(
            "x = 1\n", sources["wirecheck"], sources["replica"]
        )
        assert any("FRAME_FIELDS" in v.message for v in got)
        got = wire_contract.check_replication_framing(
            sources["codec"], "x = 1\n", sources["replica"]
        )
        assert any("REPLICA_FRAME_FIELDS" in v.message for v in got)
        got = wire_contract.check_replication_framing(
            sources["codec"], sources["wirecheck"], "package x\n"
        )
        assert any("replicaFrameFields" in v.message for v in got)

    def test_stale_pb2_caught(self, sources):
        # a field added to the proto but absent from the emitted module
        grown = self._edit(
            sources["proto"],
            "message AssignRequest {\n  string snapshot_id = 1;",
            "message AssignRequest {\n  string snapshot_id = 1;\n"
            "  int64 deadline_ms = 3;",
        )
        got = wire_contract.check_pb2_descriptor(grown)
        assert any(
            "AssignRequest.deadline_ms missing" in v.message for v in got
        )


class TestSuppressionParsing:
    def test_multi_rule_and_reason_forms(self):
        sups = parse_suppressions(
            "x = 1  # koordlint: disable=retrace-hazard\n"
            "# koordlint: disable=broad-except(reason: probe), donation-safety\n"
        )
        assert sups[1] == {"retrace-hazard"}
        assert sups[2] == {"broad-except", "donation-safety"}

    def test_tags_inside_string_literals_are_not_suppressions(self):
        """A docstring or message string MENTIONING the tag must not
        exempt a violation on or below its line — only real comment
        tokens count (the blanket-suppression hole the tool's docstring
        promises cannot happen)."""
        # the string literal sits on the line directly above the
        # handler — exactly where a real tag would suppress it
        got = lint('''
        def g():
            try:
                x = "# koordlint: disable=broad-except(<reason>)"
            except Exception:
                pass
        ''')
        assert [(v.rule, v.line) for v in got] == [("broad-except", 5)]
        # the same text as a REAL comment does suppress
        assert lint('''
        def g():
            try:
                risky()
            # koordlint: disable=broad-except(probe)
            except Exception:
                pass
        ''') == []

    def test_reason_text_cannot_leak_into_rule_set(self):
        # rule-shaped words INSIDE a reason must not suppress other rules,
        # even with a space before the parenthesis
        sups = parse_suppressions(
            "# koordlint: disable=broad-except (retrace-hazard noise here)\n"
            "# koordlint: disable=broad-except(x) because donation-safety\n"
        )
        assert sups[1] == {"broad-except"}
        assert sups[2] == {"broad-except"}

    def test_find_repo_root(self):
        assert find_repo_root(os.path.join(REPO, "tests")) == REPO


class TestGoToolchainGate:
    """`go vet` + `gofmt -l` for go/ when a toolchain exists; skip (not
    fail) when absent — the protoc-skip convention from PR 1."""

    def test_gofmt_clean(self):
        gofmt = shutil.which("gofmt")
        if gofmt is None:
            pytest.skip("no Go toolchain in this image (gofmt absent)")
        out = subprocess.run(
            [gofmt, "-l", os.path.join(REPO, "go")],
            capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip() == "", (
            f"gofmt would reformat: {out.stdout}"
        )

    def test_go_vet_scorerclient(self):
        go = shutil.which("go")
        if go is None:
            pytest.skip("no Go toolchain in this image (go absent)")
        proc = subprocess.run(
            [go, "vet", "./..."],
            cwd=os.path.join(REPO, "go", "scorerclient"),
            capture_output=True, text=True, timeout=300,
        )
        if proc.returncode != 0 and re.search(
            r"(no required module provides|missing go\.sum|dial tcp|"
            r"cannot find module|proxy\.golang\.org|connection refused)",
            proc.stderr,
        ):
            pytest.skip(
                "go vet needs the module graph and the network is "
                f"unavailable: {proc.stderr.strip()[:200]}"
            )
        assert proc.returncode == 0, proc.stderr


class TestUnboundedWait:
    """unbounded-wait (ISSUE 13): no-timeout Condition/Event waits and
    deadline-less client stub calls fail lint; the backstop idiom,
    bounded waits and reasoned suppressions stay clean."""

    def test_bare_event_wait_caught(self):
        out = lint(
            """
            import threading

            def park(done):
                done.wait()
            """,
            ["unbounded-wait"],
        )
        assert len(out) == 1
        assert out[0].rule == "unbounded-wait"
        assert "timeout" in out[0].message

    def test_bare_condition_wait_caught(self):
        out = lint(
            """
            def drain(self):
                with self._cond:
                    while not self._frames:
                        self._cond.wait()
            """,
            ["unbounded-wait"],
        )
        assert len(out) == 1

    def test_backstop_idiom_clean(self):
        out = lint(
            """
            def follow(self, entry):
                with self._cond:
                    while not entry.done:
                        self._cond.wait(timeout=1.0)
                while not entry.flag.wait(timeout=1.0):
                    pass
            """,
            ["unbounded-wait"],
        )
        assert out == []

    def test_positional_timeout_clean(self):
        out = lint(
            """
            def join(self, t):
                t.wait(5.0)
            """,
            ["unbounded-wait"],
        )
        assert out == []

    def test_stub_call_without_timeout_caught(self):
        out = lint(
            """
            def call(self, request):
                stub = self._score_stub()
                return stub(request)
            """,
            ["unbounded-wait"],
        )
        assert len(out) == 1
        assert "stub" in out[0].message

    def test_stub_call_with_timeout_clean(self):
        out = lint(
            """
            def call(self, request):
                stub = self._score_stub()
                return stub(request, timeout=self._timeout_s())
            """,
            ["unbounded-wait"],
        )
        assert out == []

    def test_stub_factory_call_clean(self):
        # zero-arg calls are stub FACTORIES, not RPC invocations
        out = lint(
            """
            def pick(self):
                return self._leader_score_stub()
            """,
            ["unbounded-wait"],
        )
        assert out == []

    def test_kwargs_splat_not_flagged(self):
        # a **kw splat may carry timeout=: cannot prove it missing
        out = lint(
            """
            def call(self, request, **kw):
                return stub(request, **kw)
            """,
            ["unbounded-wait"],
        )
        assert out == []

    def test_suppression_honored(self):
        out = lint(
            """
            import threading

            def main():
                threading.Event().wait()  # koordlint: disable=unbounded-wait(main thread parks forever by design)
            """,
            ["unbounded-wait"],
        )
        assert out == []


class TestWirecheckMessageMirror:
    """wire-contract's third-mirror extension (ISSUE 13): the
    hand-rolled wirecheck.py decoders are statically diffed against
    the proto so a new field (the deadline/band/degraded additions
    being the motivating case) cannot be silently dropped by the
    independent mirror."""

    PROTO = """
    message ScoreRequest {
      string snapshot_id = 1;
      int64 top_k = 2;
      bool flat = 3;
      int64 deadline_ms = 4;
    }
    """

    GOOD = '''
def decode_score_request(b):
    r = {"snapshot_id": "", "top_k": 0, "flat": False, "deadline_ms": 0}
    for field, _wtype, v in split_fields(b):
        if field == 1:
            r["snapshot_id"] = v.decode("utf-8")
        elif field == 2:
            r["top_k"] = _signed(v)
        elif field == 3:
            r["flat"] = bool(v)
        elif field == 4:
            r["deadline_ms"] = _signed(v)
    return r
'''

    def test_matching_mirror_clean(self):
        out = wire_contract.check_wirecheck_messages(
            textwrap.dedent(self.PROTO), self.GOOD
        )
        assert out == []

    def test_missing_branch_caught(self):
        src = self.GOOD.replace(
            '        elif field == 4:\n'
            '            r["deadline_ms"] = _signed(v)\n', ''
        )
        out = wire_contract.check_wirecheck_messages(
            textwrap.dedent(self.PROTO), src
        )
        assert len(out) == 1
        assert "field == 4" in out[0].message
        assert "deadline_ms" in out[0].message

    def test_wrong_key_caught(self):
        src = self.GOOD.replace('r["deadline_ms"] = _signed(v)',
                                'r["deadline"] = _signed(v)')
        out = wire_contract.check_wirecheck_messages(
            textwrap.dedent(self.PROTO), src
        )
        assert len(out) == 1
        assert "deadline_ms" in out[0].message

    def test_phantom_field_caught(self):
        src = self.GOOD.replace(
            'elif field == 4:',
            'elif field == 9:\n            r["ghost"] = v\n'
            '        elif field == 4:'
        )
        out = wire_contract.check_wirecheck_messages(
            textwrap.dedent(self.PROTO), src
        )
        assert len(out) == 1
        assert "field 9" in out[0].message

    def test_missing_decoder_caught(self):
        out = wire_contract.check_wirecheck_messages(
            textwrap.dedent(self.PROTO), "def unrelated():\n    pass\n"
        )
        assert len(out) == 1
        assert "decode_score_request" in out[0].message

    def test_repo_wirecheck_mirror_is_clean(self):
        out = wire_contract.check_wirecheck_messages(
            read("koordinator_tpu", "bridge", "scorer.proto"),
            read("koordinator_tpu", "bridge", "wirecheck.py"),
        )
        assert out == [], "\n".join(v.format() for v in out)


# ---- ISSUE 17: whole-program lock graph + guarded-state inference ----


def lockcheck(sources, md_text="GENERATE"):
    """Run the lock-graph pass over synthetic sources.  By default the
    doc is generated from the same graph, so only cycle / witness-name
    violations surface; pass explicit md_text (or None) to exercise the
    drift directions."""
    from koordinator_tpu.analysis import lockgraph

    srcs = {p: textwrap.dedent(s) for p, s in sources.items()}
    if md_text == "GENERATE":
        md_text = lockgraph.generate_lockorder_md(lockgraph.build_graph(srcs))
    return lockgraph.check_sources(srcs, md_text)


class TestLockOrderCycle:
    def test_direct_nesting_cycle_caught(self):
        got = lockcheck({"eng.py": """
        import threading

        class Engine:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def fwd(self):
                with self._a:
                    with self._b:
                        pass

            def rev(self):
                with self._b:
                    with self._a:
                        pass
        """})
        assert [v.rule for v in got] == ["lock-order-cycle"]
        assert "eng.Engine._a" in got[0].message
        assert "eng.Engine._b" in got[0].message
        assert "deadlock" in got[0].message

    def test_cross_module_call_cycle_caught(self):
        # neither module nests both locks lexically: the cycle only
        # exists through the cross-module method table
        got = lockcheck({
            "pmod.py": """
            import threading
            from qmod import Q

            class P:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.q = Q(self)

                def outer(self):
                    with self._lock:
                        self.q.enter_q()

                def locked_op(self):
                    with self._lock:
                        pass
            """,
            "qmod.py": """
            import threading

            class Q:
                def __init__(self, p):
                    self._lock = threading.Lock()
                    self.p = p

                def enter_q(self):
                    with self._lock:
                        pass

                def back(self):
                    with self._lock:
                        self.p.locked_op()
            """,
        })
        assert [v.rule for v in got] == ["lock-order-cycle"]
        assert "pmod.P._lock" in got[0].message
        assert "qmod.Q._lock" in got[0].message

    def test_condition_wait_reacquire_closes_cycle(self):
        # cond -> y from the nesting, and wait() re-acquires cond while
        # y is STILL held (the stdlib releases only the condition):
        # y -> cond — the hidden inversion a plain `with cond:` in
        # another thread deadlocks against
        got = lockcheck({"w.py": """
        import threading

        class W:
            def __init__(self):
                self._y = threading.Lock()
                self._cond = threading.Condition()

            def park(self):
                with self._cond:
                    with self._y:
                        self._cond.wait(timeout=1.0)
        """})
        assert [v.rule for v in got] == ["lock-order-cycle"]
        assert "Condition.wait reacquire" in got[0].message

    def test_lexical_acquire_holds_rest_of_block(self):
        # .acquire() (no with) still orders later acquisitions
        got = lockcheck({"acq.py": """
        import threading

        class A:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def m(self):
                self._a.acquire()
                with self._b:
                    pass
                self._a.release()

            def n(self):
                with self._b:
                    with self._a:
                        pass
        """})
        assert [v.rule for v in got] == ["lock-order-cycle"]

    def test_clean_hierarchy_passes(self):
        got = lockcheck({"clean.py": """
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self._c = threading.Lock()

            def m(self):
                with self._a:
                    with self._b:
                        with self._c:
                            pass

            def n(self):
                with self._b:
                    with self._c:
                        pass
        """})
        assert got == [], "\n".join(v.format() for v in got)

    def test_same_identity_nesting_is_not_a_cycle(self):
        # two instances share one identity; self-edges carry no order
        got = lockcheck({"dup.py": """
        import threading

        class D:
            def __init__(self):
                self._lock = threading.Lock()

            def m(self, other):
                with self._lock:
                    with other._lock:
                        pass
        """})
        assert got == [], "\n".join(v.format() for v in got)


class TestLockOrderDocDrift:
    TWO_LOCKS = {
        "two.py": """
        import threading

        class T:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def m(self):
                with self._a:
                    with self._b:
                        pass
        """,
    }

    def test_missing_doc_fails(self):
        got = lockcheck(self.TWO_LOCKS, md_text=None)
        assert [v.rule for v in got] == ["lockorder-doc-drift"]
        assert "not found" in got[0].message

    def test_derived_edge_missing_from_doc_fails(self):
        from koordinator_tpu.analysis import lockgraph

        srcs = {p: textwrap.dedent(s) for p, s in self.TWO_LOCKS.items()}
        md = lockgraph.generate_lockorder_md(lockgraph.build_graph(srcs))
        gutted = "\n".join(
            ln for ln in md.splitlines() if " | `two.T._b` | " not in ln
        )
        got = lockcheck(self.TWO_LOCKS, md_text=gutted)
        assert any("missing from" in v.message and v.rule ==
                   "lockorder-doc-drift" for v in got)

    def test_doc_edge_nothing_derives_fails(self):
        from koordinator_tpu.analysis import lockgraph

        srcs = {p: textwrap.dedent(s) for p, s in self.TWO_LOCKS.items()}
        md = lockgraph.generate_lockorder_md(lockgraph.build_graph(srcs))
        # a phantom reversed edge row nothing derives
        md += "| `two.T._b` | `two.T._a` | two.py:1 | nested with |\n"
        got = lockcheck(self.TWO_LOCKS, md_text=md)
        assert any("no code path derives" in v.message for v in got)

    def test_byte_stale_doc_fails(self):
        from koordinator_tpu.analysis import lockgraph

        srcs = {p: textwrap.dedent(s) for p, s in self.TWO_LOCKS.items()}
        md = lockgraph.generate_lockorder_md(lockgraph.build_graph(srcs))
        got = lockcheck(self.TWO_LOCKS, md_text=md + "\ntrailing edit\n")
        assert [v.rule for v in got] == ["lockorder-doc-drift"]
        assert "stale" in got[0].message

    def test_witness_factory_name_mismatch_fails(self):
        got = lockcheck({"wn.py": """
        from koordinator_tpu.obs.lockwitness import witness_lock

        class N:
            def __init__(self):
                self._lock = witness_lock("wrong.identity")
        """})
        assert any("witness factory" in v.message and v.rule ==
                   "lockorder-doc-drift" for v in got)
        assert any("wn.N._lock" in v.message for v in got)

    def test_witness_factory_correct_name_passes(self):
        got = lockcheck({"wn.py": """
        from koordinator_tpu.obs.lockwitness import witness_lock

        class N:
            def __init__(self):
                self._lock = witness_lock("wn.N._lock")
        """})
        assert got == [], "\n".join(v.format() for v in got)

    def test_repo_doc_regenerates_byte_identical(self):
        from koordinator_tpu.analysis import lockgraph

        want = lockgraph.generate_lockorder_md(lockgraph.repo_graph(REPO))
        assert read("docs", "LOCKORDER.md") == want, (
            "docs/LOCKORDER.md is stale — run "
            "`python -m koordinator_tpu.analysis --write-lockorder`"
        )


class TestUnguardedSharedState:
    def test_lock_free_write_of_guarded_attr_caught(self):
        got = lint("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._count = 0

            def bump(self):
                with self._lock:
                    self._count += 1

            def reset(self):
                self._count = 0
        """, ["unguarded-shared-state"])
        assert [(v.rule, v.line) for v in got] == \
            [("unguarded-shared-state", 14)]
        assert "two writers race" in got[0].message

    def test_lock_free_read_of_mutated_structure_caught(self):
        got = lint("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = {}

            def put(self, k, v):
                with self._lock:
                    self._items[k] = v

            def dump(self):
                return list(self._items.values())
        """, ["unguarded-shared-state"])
        assert len(got) == 1
        assert "mutated in place" in got[0].message

    def test_init_writes_exempt(self):
        got = lint("""
        import threading

        class S:
            def __init__(self, seed):
                self._lock = threading.Lock()
                self._count = seed
                self._count += 1

            def bump(self):
                with self._lock:
                    self._count += 1
        """, ["unguarded-shared-state"])
        assert got == [], "\n".join(v.format() for v in got)

    def test_rebind_only_attr_atomic_read_exempt(self):
        # the guarded writes only REBIND (no in-place mutation): a
        # lock-free read sees either the old or the new object — atomic
        got = lint("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._snapshot = None

            def publish(self, snap):
                with self._lock:
                    self._snapshot = snap

            def peek(self):
                return self._snapshot
        """, ["unguarded-shared-state"])
        assert got == [], "\n".join(v.format() for v in got)

    def test_locked_suffix_method_exempt(self):
        # *_locked methods run with the lock already held by contract
        got = lint("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._count = 0

            def bump(self):
                with self._lock:
                    self._bump_locked()

            def _bump_locked(self):
                self._count += 1
        """, ["unguarded-shared-state"])
        assert got == [], "\n".join(v.format() for v in got)

    def test_reasoned_suppression_honored(self):
        got = lint("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._count = 0

            def bump(self):
                with self._lock:
                    self._count += 1

            def reset(self):
                # koordlint: disable=unguarded-shared-state(reason: boot only)
                self._count = 0
        """, ["unguarded-shared-state"])
        assert got == [], "\n".join(v.format() for v in got)

    def test_class_without_lock_ignored(self):
        got = lint("""
        class Plain:
            def __init__(self):
                self._count = 0

            def bump(self):
                self._count += 1
        """, ["unguarded-shared-state"])
        assert got == [], "\n".join(v.format() for v in got)


class TestSuppressionAudit:
    def test_parse_tags_extracts_rules_and_reasons(self):
        from koordinator_tpu.analysis import suppressions

        tags = suppressions.parse_tags("f.py", textwrap.dedent("""
        x = 1  # koordlint: disable=broad-except(reason: logged upstream)
        y = 2  # koordlint: disable=bare-retry
        # koordlint: disable=broad-except(a), unbounded-wait
        """))
        got = [(t.line, t.rule, t.reason) for t in tags]
        assert got == [
            (2, "broad-except", "reason: logged upstream"),
            (3, "bare-retry", None),
            (4, "broad-except", "a"),
            (4, "unbounded-wait", None),
        ]

    def test_repo_audit_is_clean(self):
        from koordinator_tpu.analysis import suppressions

        tags, problems = suppressions.audit(REPO)
        assert problems == [], "\n".join(p.format() for p in problems)
        # every reason-required tag in the repo carries its reason
        for tag in tags:
            if tag.rule in suppressions.REASON_REQUIRED:
                assert tag.reason, f"{tag.path}:{tag.line} missing reason"

    def test_cli_suppressions_flag_exits_zero(self, capsys):
        from koordinator_tpu.analysis.__main__ import main

        assert main(["--suppressions", "--root", REPO]) == 0
        out = capsys.readouterr().out
        assert "live suppression(s)" in out
        assert "audit clean" in out

    def test_format_report_flags_problems(self):
        from koordinator_tpu.analysis.core import Violation
        from koordinator_tpu.analysis import suppressions

        report = suppressions.format_report(
            [suppressions.Tag("f.py", 3, "broad-except", None)],
            [Violation("suppression-audit", "f.py", 3, "no reason")],
        )
        assert "NO REASON" in report
        assert "AUDIT FAILED: 1 problem(s)" in report


class TestUnregisteredJitBoundary:
    """Seeded regressions for the device-time-truth rule (ISSUE 19):
    serving-path jit boundaries must register with the launch ledger."""

    R = ["unregistered-jit-boundary"]

    def _lint(self, src, path="koordinator_tpu/solver/fixture.py"):
        return run_rules_on_source(path, textwrap.dedent(src), self.R)

    def test_unregistered_jitted_def_fires(self):
        vs = self._lint("""
        from functools import partial
        import jax

        @partial(jax.jit, static_argnames=("cfg",))
        def _score(snapshot, cfg):
            return snapshot
        """)
        assert len(vs) == 1
        assert "no @devprof.boundary" in vs[0].message

    def test_registered_jitted_def_is_clean(self):
        vs = self._lint("""
        from functools import partial
        import jax
        from koordinator_tpu.obs import devprof

        @devprof.boundary("solver.fixture._score")
        @partial(jax.jit, static_argnames=("cfg",))
        def _score(snapshot, cfg):
            return snapshot
        """)
        assert vs == []

    def test_boundary_below_jit_fires_order_violation(self):
        # decorators apply bottom-up: boundary below jit wraps the raw
        # function and the AOT compile capture has nothing to .lower()
        vs = self._lint("""
        from functools import partial
        import jax
        from koordinator_tpu.obs import devprof

        @partial(jax.jit, static_argnames=("cfg",))
        @devprof.boundary("solver.fixture._score")
        def _score(snapshot, cfg):
            return snapshot
        """)
        assert len(vs) == 1
        assert "BELOW" in vs[0].message

    def test_non_literal_boundary_name_fires(self):
        vs = self._lint("""
        import jax
        from koordinator_tpu.obs import devprof

        NAME = "solver.fixture._score"

        @devprof.boundary(NAME)
        @jax.jit
        def _score(x):
            return x
        """)
        assert len(vs) == 1
        assert "string literal" in vs[0].message

    def test_jit_call_form_assignment_fires(self):
        vs = self._lint("""
        import jax

        def _scatter(arr, idx):
            return arr

        scatter = jax.jit(_scatter, donate_argnums=(0,))
        """)
        assert len(vs) == 1
        assert "call-form" in vs[0].message

    def test_shard_map_outside_jit_fires(self):
        vs = self._lint("""
        from koordinator_tpu.parallel.mesh import shard_map_compat

        def helper(mesh, x):
            return shard_map_compat(
                lambda a: a, mesh=mesh, in_specs=None, out_specs=None
            )(x)
        """)
        assert len(vs) == 1
        assert "shard_map launch outside" in vs[0].message

    def test_shard_map_inside_registered_jit_is_clean(self):
        vs = self._lint("""
        from functools import partial
        import jax
        from koordinator_tpu.obs import devprof
        from koordinator_tpu.parallel.mesh import shard_map_compat

        @devprof.boundary("solver.fixture._sharded")
        @partial(jax.jit, static_argnames=("mesh",))
        def _sharded(x, *, mesh):
            return shard_map_compat(
                lambda a: a, mesh=mesh, in_specs=None, out_specs=None
            )(x)
        """)
        assert vs == []

    def test_out_of_scope_module_is_exempt(self):
        # harness/test modules never sit on the Score/Assign path
        vs = self._lint("""
        import jax

        @jax.jit
        def scenario_kernel(x):
            return x
        """, path="koordinator_tpu/harness/fixture.py")
        assert vs == []

    def test_suppression_with_reason_is_honored(self):
        vs = self._lint("""
        import jax

        @jax.jit  # koordlint: disable=unregistered-jit-boundary(reason: cold-path migration helper, never on the serving path)
        def _migrate(x):
            return x
        """)
        assert vs == []

    def test_reason_required_for_suppression(self):
        from koordinator_tpu.analysis import suppressions

        assert "unregistered-jit-boundary" in suppressions.REASON_REQUIRED


class TestPrewarmDrift:
    """ISSUE 20: the prewarm tables in obs/prewarm.py partition the
    registered boundary space — one-sided drift against the repo's
    ``@devprof.boundary`` registrations must fail lint in BOTH
    directions (the metrics-doc-drift shape applied to the AOT replay
    contract)."""

    REGISTRATIONS = [
        ("solver.candidates._build", "koordinator_tpu/solver/candidates.py", 10),
        ("solver.candidates._build_sharded", "koordinator_tpu/solver/candidates.py", 20),
        ("solver.topk.masked_top_k", "koordinator_tpu/solver/topk.py", 5),
    ]
    PREWARM_FIXTURE = textwrap.dedent('''
        PREWARM_BOUNDARIES = (
            "solver.candidates._build",
            "solver.topk.masked_top_k",
        )

        PREWARM_EXCLUDED = {
            "solver.candidates._build_sharded": "mesh static is process-local",
        }
    ''')

    def test_aligned_sources_are_clean(self):
        from koordinator_tpu.analysis import prewarmdrift

        assert prewarmdrift.diff_prewarm(
            self.REGISTRATIONS, self.PREWARM_FIXTURE
        ) == []

    def test_head_is_clean(self):
        from koordinator_tpu.analysis import prewarmdrift

        root = find_repo_root(REPO)
        assert prewarmdrift.check_repo(root) == []

    def test_registered_but_untabled_caught(self):
        from koordinator_tpu.analysis import prewarmdrift

        regs = self.REGISTRATIONS + [
            ("solver.wave._wave_assign", "koordinator_tpu/solver/wave.py", 42),
        ]
        got = prewarmdrift.diff_prewarm(regs, self.PREWARM_FIXTURE)
        assert len(got) == 1
        assert got[0].rule == "prewarm-drift"
        assert "solver.wave._wave_assign" in got[0].message
        assert "absent from both prewarm tables" in got[0].message
        # flags the registration's own file and line
        assert got[0].path.endswith("wave.py")
        assert got[0].line == 42

    def test_stale_replay_row_caught(self):
        from koordinator_tpu.analysis import prewarmdrift

        regs = [r for r in self.REGISTRATIONS
                if r[0] != "solver.topk.masked_top_k"]
        got = prewarmdrift.diff_prewarm(regs, self.PREWARM_FIXTURE)
        assert len(got) == 1
        assert "solver.topk.masked_top_k" in got[0].message
        assert "stale replay row" in got[0].message
        # flags the prewarm.py table entry's line
        assert got[0].path.endswith("prewarm.py")
        assert got[0].line > 0

    def test_stale_exclusion_caught(self):
        from koordinator_tpu.analysis import prewarmdrift

        regs = [r for r in self.REGISTRATIONS
                if r[0] != "solver.candidates._build_sharded"]
        got = prewarmdrift.diff_prewarm(regs, self.PREWARM_FIXTURE)
        assert len(got) == 1
        assert "stale exclusion" in got[0].message
        assert got[0].path.endswith("prewarm.py")

    def test_double_listing_caught(self):
        from koordinator_tpu.analysis import prewarmdrift

        both = self.PREWARM_FIXTURE.replace(
            '"solver.candidates._build",',
            '"solver.candidates._build",\n    '
            '"solver.candidates._build_sharded",',
        )
        got = prewarmdrift.diff_prewarm(self.REGISTRATIONS, both)
        assert any(
            "BOTH" in v.message
            and "solver.candidates._build_sharded" in v.message
            for v in got
        )

    def test_registration_parser_skips_docstring_examples(self):
        from koordinator_tpu.analysis import prewarmdrift

        src = textwrap.dedent('''
            from koordinator_tpu.obs import devprof

            def helper():
                """Example:

                    @devprof.boundary("solver.fake.from_docstring")
                    def f(x): ...
                """

            @devprof.boundary("solver.real.registered")
            def real(x):
                return x
        ''')
        got = prewarmdrift.parse_boundary_registrations(src)
        assert [name for name, _ in got] == ["solver.real.registered"]

    def test_vanished_tables_fail_loudly(self):
        from koordinator_tpu.analysis import prewarmdrift

        got = prewarmdrift.diff_prewarm(self.REGISTRATIONS, "X = 1\n")
        assert any(
            "no PREWARM_BOUNDARIES / PREWARM_EXCLUDED" in v.message
            for v in got
        )

    def test_rule_is_registered_and_runs_in_run_repo(self):
        assert "prewarm-drift" in RULES
        assert run_repo(root=REPO, rules=["prewarm-drift"]) == []
