"""Tests for the koord-manager analog: sloconfig merge, batch/mid
overcommit math (reference ``batchresource/util.go:38-49`` semantics),
degrade behavior, NodeSLO rendering, profile mutation, quota profiles."""

import numpy as np
import pytest

from koordinator_tpu.manager import (
    calculate_batch_resource,
    calculate_mid_resource,
    default_colocation_strategy,
    is_strategy_valid,
    merge_node_strategy,
    need_sync,
)
from koordinator_tpu.manager import nodemetric, nodeslo, profile, quota_profile
from koordinator_tpu.manager.noderesource import (
    batch_allocatable_batch,
    priority_class_of,
)
from koordinator_tpu.manager.sloconfig import parse_strategy
from koordinator_tpu.model import resources as res

Gi = 1024**3
Gi_M = 1024  # 1 GiB on the dense MiB-unit axis


class TestSloConfig:
    def test_defaults_valid(self):
        s = default_colocation_strategy()
        assert is_strategy_valid(s)
        assert s.cpu_reclaim_threshold_percent == 60
        assert s.memory_reclaim_threshold_percent == 65
        assert s.degrade_time_minutes == 15

    def test_parse_overrides(self):
        s = parse_strategy({"enable": True, "cpuReclaimThresholdPercent": 70})
        assert s.enable and s.cpu_reclaim_threshold_percent == 70
        # untouched fields keep defaults
        assert s.memory_reclaim_threshold_percent == 65

    def test_invalid_on_nonpositive(self):
        s = default_colocation_strategy().replace(degrade_time_minutes=0)
        assert not is_strategy_valid(s)

    def test_node_merge_selector(self):
        cluster = default_colocation_strategy()
        cfgs = [
            {
                "nodeSelector": {"matchLabels": {"pool": "batch"}},
                "strategy": {"cpuReclaimThresholdPercent": 80},
            }
        ]
        merged = merge_node_strategy(cluster, {"pool": "batch"}, cfgs)
        assert merged.cpu_reclaim_threshold_percent == 80
        unmatched = merge_node_strategy(cluster, {"pool": "prod"}, cfgs)
        assert unmatched.cpu_reclaim_threshold_percent == 60


class TestBatchResource:
    """Hand-computed fixtures for
    batchAllocatable = capacity - nodeReservation - systemUsedOrReserved - podHPUsed."""

    def strategy(self):
        return default_colocation_strategy()

    def test_basic_formula(self):
        # 100-core node (100000m), 100Gi. reclaim 60% cpu / 65% mem
        # => nodeReservation = 40000m cpu, 35Gi mem.
        out = calculate_batch_resource(
            self.strategy(),
            node_capacity={"cpu": "100", "memory": 100 * Gi},
            node_annotation_reserved=None,
            kubelet_reserved=None,
            system_usage={"cpu": "7", "memory": 12 * Gi},
            pods=[
                {
                    "name": "prod-a",
                    "requests": {"cpu": "10", "memory": 20 * Gi},
                    "priority_class": "koord-prod",
                },
                {
                    "name": "batch-b",
                    "requests": {"cpu": "30", "memory": 30 * Gi},
                    "priority_class": "koord-batch",
                },
            ],
            pod_metrics={
                "prod-a": {"cpu": "11", "memory": 18 * Gi},
                "batch-b": {"cpu": "5", "memory": 5 * Gi},
            },
            metric_update_time=1000.0,
            now=1000.0,
        )
        assert not out.degraded
        # HP used = prod-a metric (11000m, 18Gi); batch-b ignored.
        assert out.batch_cpu_milli == 100000 - 40000 - 7000 - 11000
        assert out.batch_memory_mib == (100 - 35 - 12 - 18) * Gi_M

    def test_memory_by_request_policy(self):
        s = self.strategy().replace(memory_calculate_policy="request")
        out = calculate_batch_resource(
            s,
            node_capacity={"cpu": "100", "memory": 100 * Gi},
            node_annotation_reserved={"memory": 2 * Gi},
            kubelet_reserved=None,
            system_usage={"cpu": "7", "memory": 12 * Gi},
            pods=[
                {
                    "name": "prod-a",
                    "requests": {"cpu": "10", "memory": 20 * Gi},
                    "priority_class": "koord-prod",
                }
            ],
            pod_metrics={"prod-a": {"cpu": "11", "memory": 18 * Gi}},
            metric_update_time=0.0,
            now=0.0,
        )
        # memory: capacity - reservation(35Gi) - systemReserved(2Gi) - HPrequest(20Gi)
        assert out.batch_memory_mib == (100 - 35 - 2 - 20) * Gi_M
        # cpu still byUsage
        assert out.batch_cpu_milli == 100000 - 40000 - 7000 - 11000

    def test_lse_pod_cpu_by_request(self):
        out = calculate_batch_resource(
            self.strategy(),
            node_capacity={"cpu": "100", "memory": 100 * Gi},
            node_annotation_reserved=None,
            kubelet_reserved=None,
            system_usage={},
            pods=[
                {
                    "name": "lse-a",
                    "requests": {"cpu": "10", "memory": 10 * Gi},
                    "priority_class": "koord-prod",
                    "qos": "LSE",
                }
            ],
            pod_metrics={"lse-a": {"cpu": "2", "memory": 4 * Gi}},
            metric_update_time=0.0,
            now=0.0,
        )
        # LSE: cpu by request (10), memory by usage (4Gi)
        assert out.batch_cpu_milli == 100000 - 40000 - 0 - 10000
        assert out.batch_memory_mib == (100 - 35 - 4) * Gi_M

    def test_unknown_metric_pods_count_hp(self):
        out = calculate_batch_resource(
            self.strategy(),
            node_capacity={"cpu": "100", "memory": 100 * Gi},
            node_annotation_reserved=None,
            kubelet_reserved=None,
            system_usage={},
            pods=[],
            pod_metrics={"ghost": {"cpu": "3", "memory": 3 * Gi}},
            metric_update_time=0.0,
            now=0.0,
        )
        assert out.batch_cpu_milli == 100000 - 40000 - 3000

    def test_degrade_on_stale_metric(self):
        out = calculate_batch_resource(
            self.strategy(),
            node_capacity={"cpu": "100", "memory": 100 * Gi},
            node_annotation_reserved=None,
            kubelet_reserved=None,
            system_usage={},
            pods=[],
            pod_metrics={},
            metric_update_time=0.0,
            now=16 * 60.0,  # > DegradeTimeMinutes(15)
        )
        assert out.degraded
        assert out.as_extended_resources() == {}

    def test_cpu_normalization_amplification(self):
        out = calculate_batch_resource(
            self.strategy(),
            node_capacity={"cpu": "100", "memory": 100 * Gi},
            node_annotation_reserved=None,
            kubelet_reserved=None,
            system_usage={},
            pods=[],
            pod_metrics={},
            metric_update_time=0.0,
            now=0.0,
            cpu_normalization_ratio=1.2,
        )
        assert out.batch_cpu_milli == int(60000 * 1.2)

    def test_vectorized_matches_scalar(self):
        s = self.strategy()
        rng = np.random.RandomState(0)
        n = 64
        cap = np.stack(
            [rng.randint(8000, 128000, n), rng.randint(16, 256, n) * Gi_M], axis=1
        ).astype(np.int64)
        sysres = np.stack([rng.randint(0, 2000, n), rng.randint(0, 4, n) * Gi_M], axis=1).astype(np.int64)
        sysuse = np.stack([rng.randint(0, 4000, n), rng.randint(0, 8, n) * Gi_M], axis=1).astype(np.int64)
        hpreq = np.stack([rng.randint(0, 64000, n), rng.randint(0, 128, n) * Gi_M], axis=1).astype(np.int64)
        hpuse = np.stack([rng.randint(0, 64000, n), rng.randint(0, 128, n) * Gi_M], axis=1).astype(np.int64)
        batch = batch_allocatable_batch(s, cap, sysres, sysuse, hpreq, hpuse)
        for i in range(n):
            out = calculate_batch_resource(
                s,
                node_capacity={"cpu": f"{cap[i,0]}m", "memory": f"{cap[i,1]}Mi"},
                node_annotation_reserved={"cpu": f"{sysres[i,0]}m", "memory": f"{sysres[i,1]}Mi"},
                kubelet_reserved=None,
                system_usage={"cpu": f"{sysuse[i,0]}m", "memory": f"{sysuse[i,1]}Mi"},
                pods=[
                    {
                        "name": "hp",
                        "requests": {"cpu": f"{hpreq[i,0]}m", "memory": f"{hpreq[i,1]}Mi"},
                        "priority_class": "koord-prod",
                    }
                ],
                pod_metrics={"hp": {"cpu": f"{hpuse[i,0]}m", "memory": f"{hpuse[i,1]}Mi"}},
                metric_update_time=0.0,
                now=0.0,
            )
            assert out.batch_cpu_milli == batch[i, 0]
            assert out.batch_memory_mib == batch[i, 1]


class TestMidResource:
    def test_min_of_reclaimable_and_ratio(self):
        s = default_colocation_strategy().replace(mid_cpu_threshold_percent=10)
        out = calculate_mid_resource(
            s,
            node_allocatable={"cpu": "100", "memory": 100 * Gi},
            prod_reclaimable={"cpu": "20", "memory": 5 * Gi},
            metric_update_time=0.0,
            now=0.0,
        )
        # cpu capped by 10% of allocatable = 10000m < reclaimable 20000m
        assert out.batch_cpu_milli == 10000
        assert out.batch_memory_mib == 5 * Gi_M

    def test_degrade_without_reclaimable(self):
        out = calculate_mid_resource(
            default_colocation_strategy(),
            node_allocatable={"cpu": "100", "memory": 100 * Gi},
            prod_reclaimable=None,
        )
        assert out.degraded


class TestNeedSync:
    def test_diff_threshold(self):
        s = default_colocation_strategy()  # threshold 0.1
        old = {res.BATCH_CPU: 10000, res.BATCH_MEMORY: 100}
        assert not need_sync(s, old, {res.BATCH_CPU: 10500, res.BATCH_MEMORY: 100})
        assert need_sync(s, old, {res.BATCH_CPU: 12000, res.BATCH_MEMORY: 100})
        assert need_sync(s, old, {res.BATCH_CPU: 10000})  # resource removed


class TestPriorityClass:
    def test_band_derivation(self):
        assert priority_class_of({"priority": 9500}) == "koord-prod"
        assert priority_class_of({"priority": 5500}) == "koord-batch"
        assert priority_class_of({"priority_class": "koord-free"}) == "koord-free"
        assert priority_class_of({}) == "koord-prod"  # HP by default


class TestNodeSLO:
    def test_defaults(self):
        spec = nodeslo.default_nodeslo_spec()
        assert spec["resourceUsedThresholdWithBE"]["cpuSuppressThresholdPercent"] == 65
        assert spec["resourceUsedThresholdWithBE"]["memoryEvictThresholdPercent"] == 70
        assert spec["cpuBurstStrategy"]["cfsQuotaBurstPercent"] == 300
        assert spec["resourceQOSStrategy"]["beClass"]["cpuQOS"]["groupIdentity"] == -1
        assert spec["resourceQOSStrategy"]["beClass"]["resctrlQOS"]["catRangeEndPercent"] == 30

    def test_render_merge(self):
        spec = nodeslo.render_nodeslo(
            node_labels={"pool": "batch"},
            cluster_strategies={
                "resourceUsedThresholdWithBE": {"enable": True, "cpuSuppressThresholdPercent": 55}
            },
            node_strategies=[
                {
                    "nodeSelector": {"matchLabels": {"pool": "batch"}},
                    "strategies": {"resourceUsedThresholdWithBE": {"cpuSuppressThresholdPercent": 45}},
                }
            ],
        )
        be = spec["resourceUsedThresholdWithBE"]
        assert be["enable"] is True
        assert be["cpuSuppressThresholdPercent"] == 45  # node override wins
        assert be["memoryEvictThresholdPercent"] == 70  # default retained


class TestProfileMutation:
    def test_apply_labels_qos_priority(self):
        pod = {"name": "p", "labels": {"app": "spark"}, "requests": {"cpu": "2", "memory": Gi}}
        prof = {
            "name": "batch-profile",
            "spec": {
                "selector": {"matchLabels": {"app": "spark"}},
                "qosClass": "BE",
                "priorityClassName": "koord-batch",
                "priorityClassValue": 5500,
                "koordinatorPriority": 1111,
                "labels": {"team": "data"},
                "schedulerName": "koord-scheduler",
            },
        }
        out = profile.mutate_by_profiles(pod, [prof])
        assert out["qos"] == "BE"
        assert out["labels"]["team"] == "data"
        assert out["labels"][profile.LABEL_POD_QOS] == "BE"
        assert out["scheduler_name"] == "koord-scheduler"
        # batch pod: native resources translated to batch-* (cpu in milli)
        assert res.BATCH_CPU in out["requests"] and "cpu" not in out["requests"]
        assert out["requests"][res.BATCH_CPU] == 2000
        # round-trippable quantity string (re-encoding must not re-scale)
        assert out["requests"][res.BATCH_MEMORY] == "1024Mi"
        assert (
            res.parse_quantity(out["requests"][res.BATCH_MEMORY], res.BATCH_MEMORY)
            == Gi_M
        )

    def test_prod_pod_not_translated(self):
        pod = {"name": "p", "labels": {}, "requests": {"cpu": "2"}, "priority_class": "koord-prod"}
        out = profile.mutate_by_profiles(pod, [])
        assert "cpu" in out["requests"]

    def test_selector_mismatch_skips(self):
        pod = {"name": "p", "labels": {"app": "web"}}
        prof = {"spec": {"selector": {"matchLabels": {"app": "spark"}}, "qosClass": "BE"}}
        out = profile.mutate_by_profiles(pod, [prof])
        assert "qos" not in out


class TestQuotaProfile:
    def test_ratio_scaled_root(self):
        nodes = [
            {"name": "n1", "labels": {"pool": "a"}, "allocatable": {"cpu": "10", "memory": 10 * Gi}},
            {"name": "n2", "labels": {"pool": "a"}, "allocatable": {"cpu": "6", "memory": 6 * Gi}},
            {"name": "n3", "labels": {"pool": "b"}, "allocatable": {"cpu": "99", "memory": 99 * Gi}},
        ]
        prof = {
            "name": "pool-a",
            "spec": {
                "quotaName": "pool-a-root",
                "treeID": "tree-a",
                "nodeSelector": {"matchLabels": {"pool": "a"}},
                "resourceRatio": "0.5",
            },
        }
        out = quota_profile.reconcile_profile(prof, nodes)
        assert out["name"] == "pool-a-root"
        # quantities are emitted round-trippable (axis units + suffix)
        assert out["min"]["cpu"] == "8000m"  # (10+6 cores = 16000m) * 0.5
        assert out["min"]["memory"] == f"{8 * Gi_M}Mi"
        assert res.parse_quantity(out["min"]["memory"], "memory") == 8 * Gi_M
        assert out["labels"][quota_profile.LABEL_QUOTA_TREE_ID] == "tree-a"


class TestNodeMetricController:
    def test_reconcile_specs_and_gc(self):
        nodes = [{"name": "n1", "labels": {}}]
        existing = {"n1": {}, "gone": {}}
        s = default_colocation_strategy()
        desired = nodemetric.reconcile_nodemetrics(nodes, existing, s)
        assert desired["gone"] is None
        policy = desired["n1"]["metricCollectPolicy"]
        assert policy["aggregateDurationSeconds"] == 300
        assert policy["reportIntervalSeconds"] == 60


class TestReviewRegressions:
    def test_snake_case_node_override_applies(self):
        cluster = default_colocation_strategy().replace(cpu_reclaim_threshold_percent=50)
        merged = merge_node_strategy(
            cluster,
            {"pool": "batch"},
            [{"nodeSelector": {"matchLabels": {"pool": "batch"}},
              "strategy": {"cpu_reclaim_threshold_percent": 80}}],
        )
        assert merged.cpu_reclaim_threshold_percent == 80

    def test_unknown_override_key_ignored_not_reset(self):
        cluster = default_colocation_strategy().replace(cpu_reclaim_threshold_percent=50)
        merged = merge_node_strategy(
            cluster,
            {"pool": "batch"},
            [{"nodeSelector": {"matchLabels": {"pool": "batch"}},
              "strategy": {"someUnknownKnob": 1}}],
        )
        assert merged.cpu_reclaim_threshold_percent == 50  # untouched

    def test_free_pod_resources_not_translated(self):
        # reference ResourceNameMap has no koord-free entry
        pod = {"name": "p", "requests": {"cpu": "2", "memory": Gi}, "priority_class": "koord-free"}
        out = profile.mutate_by_profiles(pod, [])
        assert "cpu" in out["requests"] and res.BATCH_CPU not in out["requests"]


class TestJsonPatch:
    def test_add_replace_remove_ops(self):
        from koordinator_tpu.manager.webhook_server import _json_patch

        original = {"labels": {"a": "1"}, "scheduler": "default", "gone": True}
        mutated = {"labels": {"a": "2"}, "scheduler": "default", "new": 1}
        ops = {(op["op"], op["path"]) for op in _json_patch(original, mutated)}
        assert ops == {
            ("replace", "/labels"),
            ("add", "/new"),
            ("remove", "/gone"),
        }

    def test_no_change_is_empty(self):
        from koordinator_tpu.manager.webhook_server import _json_patch

        assert _json_patch({"x": 1}, {"x": 1}) == []
