"""The Go plugin's PreScore protocol, executed against the real server.

go/plugin/batchedtpuscorer.go cannot run here (no Go toolchain), so
bridge/plugin_sim.py re-states its PreScore flow step for step and these
tests drive that executable spec against the REAL raw-UDS server
(bridge/udsserver.py + ScorerServicer): the usage feed (VERDICT round-4
#3), warm-cycle delta sync (#2), generation displacement, and sidecar
restart all execute end to end.
"""

import os
import tempfile

import numpy as np
import pytest

from koordinator_tpu.bridge.plugin_sim import GoPluginSim, NUM_AXES
from koordinator_tpu.bridge.udsserver import RawUdsServer


def vec(cpu=0, mem=0, pods=0):
    v = [0] * NUM_AXES
    v[0], v[1], v[3] = cpu, mem, pods
    return v


ALLOC = vec(cpu=8000, mem=16384, pods=110)
REQ = vec(cpu=1000, mem=1024, pods=5)
POD = vec(cpu=500, mem=512, pods=1)


@pytest.fixture()
def server():
    path = os.path.join(tempfile.mkdtemp(), "scorer.sock")
    srv = RawUdsServer(path).start()
    yield path, srv
    srv.stop()


NODES = [("node-cold", ALLOC, REQ), ("node-hot", ALLOC, REQ)]


class TestUsageFeed:
    def test_hot_underrequested_node_scores_below_cold(self, server):
        """load_aware.go:269-337 semantics: with identical requests, the
        node whose MEASURED utilization is high must score below the
        cold one — the behavior usage:=requested erased (round-4 #3)."""
        path, _ = server
        sim = GoPluginSim(path)
        # hot node sits just under the 65% cpu Filter threshold so the
        # comparison exercises Score, not Filter
        sim.metrics = {
            "node-cold": vec(cpu=500, mem=512),
            "node-hot": vec(cpu=4800, mem=8192),
        }
        scores = sim.pre_score(NODES, "pod-x", POD)
        assert set(scores) == {"node-cold", "node-hot"}
        assert scores["node-cold"] > scores["node-hot"]

    def test_overloaded_node_is_filtered_entirely(self, server):
        """Usage over the 65% cpu threshold (DEFAULT_USAGE_THRESHOLDS,
        load_aware.go:185-222) removes the node from the score row —
        visible only because real usage now reaches the sidecar."""
        path, _ = server
        sim = GoPluginSim(path)
        sim.metrics = {
            "node-cold": vec(cpu=500, mem=512),
            "node-hot": vec(cpu=7000, mem=14336),
        }
        scores = sim.pre_score(NODES, "pod-x", POD)
        assert set(scores) == {"node-cold"}

    def test_without_metrics_loadaware_is_neutral(self, server):
        """No NodeMetric feed -> MetricFresh=false -> the sidecar zeroes
        the LoadAware term instead of trusting usage==requested: both
        identical nodes score the same (Fit-only)."""
        path, _ = server
        sim = GoPluginSim(path)
        scores = sim.pre_score(NODES, "pod-x", POD)
        assert scores["node-cold"] == scores["node-hot"]


class TestNodeMetricFeed:
    def test_koordlet_payload_drives_loadaware(self, server):
        """The FULL usage pipeline: a real koordlet NodeMetricReporter
        payload (metriccache -> collect) parses into the shim's usage
        vector and drives LoadAware scoring — the end-to-end wiring of
        round-4 review #3 ('populate Usage/MetricFresh from the
        NodeMetric payloads the koordlet side already produces')."""
        from koordinator_tpu.bridge.plugin_sim import (
            usage_vector_from_node_metric,
        )
        from koordinator_tpu.koordlet import metriccache as mc
        from koordinator_tpu.koordlet.metriccache import MetricCache
        from koordinator_tpu.koordlet.statesinformer import (
            NodeMetricReporter,
            StatesInformer,
        )

        def payload_for(cores_used: float):
            cache = MetricCache()
            for i in range(10):
                cache.append(mc.NODE_CPU_USAGE, cores_used, ts=float(i))
                cache.append(mc.NODE_MEMORY_USAGE, 2 * (1 << 30), ts=float(i))
            return NodeMetricReporter(cache, StatesInformer()).collect(10.0)

        hot = usage_vector_from_node_metric(payload_for(5.0))
        cold = usage_vector_from_node_metric(payload_for(0.5))
        assert hot is not None and hot[0] == 5000 and hot[1] == 2048
        assert cold is not None and cold[0] == 500
        assert usage_vector_from_node_metric({"nodeMetric": {}}) is None
        # every Kubernetes quantity serialization parses (the Go cache
        # accepts resource.Quantity forms too)
        gi = usage_vector_from_node_metric(
            {"nodeMetric": {"nodeUsage": {"cpu": "1500000000n", "memory": "2Gi"}}}
        )
        assert gi == [1500, 2048] + [0] * 11

        path, _ = server
        sim = GoPluginSim(path)
        # the informer-callback path, exactly like the Go plugin wires
        # NodeMetricCache.Set into the CR informer
        sim.update_node_metric("node-hot", payload_for(5.0))
        sim.update_node_metric("node-cold", payload_for(0.5))
        sim.update_node_metric("node-cold", {"nodeMetric": {}})  # kept
        scores = sim.pre_score(NODES, "pod-x", POD)
        assert scores["node-cold"] > scores["node-hot"]


class TestDeltaSync:
    def test_warm_cycle_ships_sparse_delta(self, server):
        """Cycle 2 against an unchanged node set must sync a sparse
        delta whose size tracks what CHANGED, not the cluster size
        (round-4 #2: the plugin used to re-ship the full table every
        pod cycle) — and produce scores identical to a cold full sync."""
        path, _ = server
        many = [(f"node-{i}", ALLOC, REQ) for i in range(64)]
        sim = GoPluginSim(path)
        sim.pre_score(many, "pod-x", POD)
        full_frame = sim.sent_frames[0][1]
        assert full_frame > 64 * 13 * 8 * 3  # three full [64,13] tensors

        # one node's committed load moves; everything else is unchanged
        nodes2 = list(many)
        nodes2[5] = ("node-5", ALLOC, vec(cpu=1500, mem=1536, pods=6))
        scores_delta = sim.pre_score(nodes2, "pod-y", POD)
        assert len(sim.sent_frames) == 4  # sync, score, sync, score
        delta_frame = sim.sent_frames[2][1]
        # 3 changed cells ride as (idx, val) pairs + the constant-size
        # single-pod table; the 20 KB node table stays home
        assert delta_frame < full_frame / 10, (
            f"warm sync {delta_frame}B should be far below full {full_frame}B"
        )

        # a cold client syncing the same view must agree exactly
        cold = GoPluginSim(path)
        assert cold.pre_score(nodes2, "pod-y", POD) == scores_delta

    def test_all_changed_falls_back_to_full(self, server):
        """More than a quarter of the table changed -> DeltaTensor ships
        the full payload (the 0.25 ratio of bridge/state.py)."""
        path, _ = server
        sim = GoPluginSim(path)
        sim.pre_score(NODES, "pod-x", POD)
        # every axis of both nodes moves: 26/26 cells changed per tensor,
        # far past the max(1, int(26*0.25)) = 6 change cap
        a2 = [9000 + i for i in range(13)]
        r2 = [3000 + i for i in range(13)]
        a3 = [7000 + i for i in range(13)]
        r3 = [2000 + i for i in range(13)]
        nodes2 = [("node-cold", a2, r2), ("node-hot", a3, r3)]
        sim.pre_score(nodes2, "pod-y", POD)
        # the warm sync is still smaller than the cold one (names are
        # omitted) but carries full tensors: much bigger than a delta
        warm = sim.sent_frames[2][1]
        assert warm > 3 * 26 * 8  # three full [2,13] i64 tensors at least


class TestDeltaProtocolFuzz:
    def test_random_mutation_displacement_restart_sequences(self, server):
        """Fuzz the warm-cycle protocol: random node-table mutations,
        foreign syncs (generation displacement), and sidecar restarts in
        one long session.  After every cycle the plugin's scores must
        equal a cold client syncing the same view — the delta baseline
        can never drift."""
        import random

        path, srv = server
        rng = random.Random(17)
        n_nodes = 12
        reqs = {f"node-{i}": list(REQ) for i in range(n_nodes)}

        def view():
            return [(name, ALLOC, list(r)) for name, r in sorted(reqs.items())]

        sim = GoPluginSim(path)
        other = GoPluginSim(path)
        servers = [srv]
        for cycle in range(20):
            action = rng.random()
            if action < 0.5:
                # mutate a few nodes' committed load
                for _ in range(rng.randrange(1, 4)):
                    r = reqs[f"node-{rng.randrange(n_nodes)}"]
                    r[0] = rng.randrange(500, 4000)
                    r[3] = rng.randrange(1, 50)
            elif action < 0.7:
                # foreign client displaces the resident generation
                other.metrics = {}
                try:
                    other.pre_score(
                        [(f"other-{i}", ALLOC, REQ) for i in range(3)],
                        f"foreign-{cycle}",
                        POD,
                    )
                except Exception:
                    other._drop_client()
            elif action < 0.8 and cycle > 0:
                # sidecar restart: resident state + connections lost
                servers[-1].stop()
                servers.append(RawUdsServer(path).start())
                other._drop_client()
                other.mirror.invalidate()

            try:
                got = sim.pre_score(view(), f"pod-{cycle}", POD)
            except Exception:
                # first cycle after a restart fails and invalidates;
                # the retry must ship full state and succeed
                assert not sim.mirror.valid
                got = sim.pre_score(view(), f"pod-{cycle}", POD)
            cold = GoPluginSim(path)
            assert cold.pre_score(view(), f"pod-{cycle}", POD) == got, (
                f"delta baseline drifted at cycle {cycle}"
            )
        servers[-1].stop()


class TestGenerationDisplacement:
    def test_foreign_sync_triggers_full_resync(self, server):
        """Another client syncs between our cycles: the generation jump
        must trigger a full re-sync (our deltas landed on a base we
        never saw), and the scores must match a cold client's."""
        path, _ = server
        sim = GoPluginSim(path)
        sim.pre_score(NODES, "pod-x", POD)

        other = GoPluginSim(path)
        other.pre_score(
            [("node-other", ALLOC, REQ), ("node-other2", ALLOC, REQ)],
            "pod-foreign",
            POD,
        )

        sim.sent_frames.clear()
        scores = sim.pre_score(NODES, "pod-y", POD)
        # delta sync + full re-sync + score = 3 frames
        methods = [m for m, _ in sim.sent_frames]
        assert methods == [1, 1, 2]
        cold = GoPluginSim(path)
        assert cold.pre_score(NODES, "pod-y", POD) == scores

    def test_sidecar_restart_recovers_within_one_cycle(self, server):
        """A restarted sidecar loses its resident tensors AND the
        connection: the warm cycle's delta Sync fails, and PreScore
        recovers IN THE SAME CYCLE by re-dialing and shipping full state
        once (ADVICE r5) — the pod's scheduling cycle never errors."""
        path, srv = server
        sim = GoPluginSim(path)
        sim.pre_score(NODES, "pod-x", POD)
        srv.stop()
        srv2 = RawUdsServer(path).start()
        try:
            sim.sent_frames.clear()
            scores = sim.pre_score(NODES, "pod-y", POD)
            assert set(scores) == {"node-cold", "node-hot"}
            # failed delta sync, full retry, score — one cycle
            methods = [m for m, _ in sim.sent_frames]
            assert methods == [1, 1, 2]
            # the retry carried full tensors (bigger than the delta frame)
            assert sim.sent_frames[1][1] > sim.sent_frames[0][1]
            assert sim.mirror.valid
            # the fresh boot's epoch was adopted as the new baseline
            cold = GoPluginSim(path)
            assert cold.pre_score(NODES, "pod-y", POD) == scores
        finally:
            srv2.stop()
