"""ISSUE 15: the fused scoring-term registry.

Three policies land as fused tensor terms in the ONE pods x nodes
launch (solver/terms.py): Gavel-style heterogeneity (throughput-matrix
gather), Synergy-style CPU/mem sensitivity profiles, and a bin-packing
objective + headroom mask.  Covered here:

* plain-numpy oracle parity — every term's device contribution is
  re-derived by an independent numpy implementation, cell for cell,
  on fuzzed snapshots with gang/quota interaction;
* Assign parity across wave in {1, 8, 32} — ``run_cycle`` with a
  term-enabled config equals ``greedy_assign`` fed the numpy oracle's
  tensors through the extras seam, bit for bit;
* mesh parity on {1, 8} devices — the mesh-resident servicer's warm
  term-delta stream is byte-identical to the single-chip servicer's;
* dirty-set attribution — sensitivity deltas dirty exactly the touched
  pod rows, a throughput-matrix delta dirties exactly the nodes of the
  touched accelerator type, accel/workload flips dirty their own rows,
  and the warm stream holds ZERO jit cache misses with all terms on;
* the term-aware serving bound — ``score_upper_bound`` covers the new
  contributions so the f32-exact top-k fast path stays exact, and
  ``masked_top_k_host`` (the brownout cache's host twin) is
  bit-identical to the device path.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from koordinator_tpu.analysis import retrace_guard
from koordinator_tpu.bridge.codegen import pb2
from koordinator_tpu.bridge.server import ScorerServicer
from koordinator_tpu.bridge.state import numpy_to_tensor
from koordinator_tpu.config import (
    CycleConfig,
    HeterogeneityTermArgs,
    PackingTermArgs,
    SensitivityTermArgs,
)
from koordinator_tpu.model import resources as res
from koordinator_tpu.model.snapshot import MAX_NODE_SCORE, encode_snapshot
from koordinator_tpu.solver import (
    greedy_assign,
    masked_top_k,
    run_cycle,
    score_cycle,
    score_upper_bound,
)
from koordinator_tpu.solver.terms import (
    default_term_config,
    term_extras,
    term_names,
    terms_upper_bound,
)
from koordinator_tpu.solver.topk import masked_top_k_host

R = res.NUM_RESOURCES

HEADROOM = {"cpu": 96, "memory": 97}

ALL_TERMS = default_term_config(packing_headroom=HEADROOM)


def _cfg_for(term: str) -> CycleConfig:
    if term == "heterogeneity":
        return CycleConfig(heterogeneity=HeterogeneityTermArgs(weight=2))
    if term == "sensitivity":
        return CycleConfig(sensitivity=SensitivityTermArgs(weight=3))
    if term == "packing":
        return CycleConfig(
            packing=PackingTermArgs(weight=2, headroom=HEADROOM)
        )
    return ALL_TERMS


# ---------------------------------------------------------------------------
# the plain-numpy oracle: an INDEPENDENT restatement of each term's
# integer math, computed from the snapshot's own padded tensors
# ---------------------------------------------------------------------------


def np_term_tensors(snap, cfg):
    """(extra_scores i64[P, N], extra_mask bool[P, N]) re-derived with
    numpy only — the reference the fused device terms must match."""
    nalloc = np.asarray(snap.nodes.allocatable, np.int64)
    nreq = np.asarray(snap.nodes.requested, np.int64)
    nuse = np.asarray(snap.nodes.usage, np.int64)
    preq = np.asarray(snap.pods.requests, np.int64)
    P, N = preq.shape[0], nalloc.shape[0]
    scores = np.zeros((P, N), np.int64)
    mask = np.ones((P, N), bool)

    def clip_term(raw, weight):
        return int(weight) * np.clip(
            raw.astype(np.int64), 0, MAX_NODE_SCORE
        )

    if cfg.heterogeneity is not None and snap.throughput is not None:
        tput = np.asarray(snap.throughput, np.int64)
        C, A = tput.shape
        wc = (
            np.clip(np.asarray(snap.pods.workload_class, np.int64), 0, C - 1)
            if snap.pods.workload_class is not None
            else np.zeros(P, np.int64)
        )
        ac = (
            np.clip(np.asarray(snap.nodes.accel_type, np.int64), 0, A - 1)
            if snap.nodes.accel_type is not None
            else np.zeros(N, np.int64)
        )
        scores = scores + clip_term(
            tput[wc[:, None], ac[None, :]], cfg.heterogeneity.weight
        )
    if cfg.sensitivity is not None and snap.pods.sensitivity is not None:
        sens = np.clip(
            np.asarray(snap.pods.sensitivity, np.int64), 0, MAX_NODE_SCORE
        )
        safe = np.where(nalloc == 0, 1, nalloc)
        occ = np.clip(nuse * MAX_NODE_SCORE // safe, 0, MAX_NODE_SCORE)
        occ = np.where(nalloc == 0, 0, occ)
        contention = (sens @ occ.T) // np.maximum(
            sens.sum(axis=-1), 1
        )[:, None]
        scores = scores + clip_term(
            MAX_NODE_SCORE - contention, cfg.sensitivity.weight
        )
    if cfg.packing is not None:
        w = np.asarray(
            res.weights_vector(dict(cfg.packing.resource_weights)), np.int64
        )
        post = nreq[None, :, :] + preq[:, None, :]
        safe = np.where(nalloc == 0, 1, nalloc)[None, :, :]
        per_res = np.minimum(post, nalloc[None]) * MAX_NODE_SCORE // safe
        per_res = np.where(nalloc[None] == 0, 0, per_res)
        wsum = int(w.sum())
        weighted = (
            (per_res * w).sum(axis=-1) // max(wsum, 1)
            if wsum
            else np.zeros((P, N), np.int64)
        )
        scores = scores + clip_term(weighted, cfg.packing.weight)
        head = np.asarray(
            res.weights_vector(dict(cfg.packing.headroom)), np.int64
        )
        if (head > 0).any():
            limited = head[None, None, :] > 0
            ok = post * 100 <= head[None, None, :] * nalloc[None, :, :]
            mask = mask & np.all(np.where(limited, ok, True), axis=-1)
    return scores, mask


def _term_lists(rng, n_nodes, n_pods, classes=3, accels=2):
    """Generator-style node/pod dict lists with gang/quota interaction
    plus the term columns, and the [C, A] throughput matrix."""
    nodes = [
        dict(
            name=f"n{i}",
            allocatable={"cpu": int(rng.integers(4000, 32000)),
                         "memory": int(rng.integers(4096, 65536)),
                         "pods": 64},
            requested={"cpu": int(rng.integers(0, 3000)),
                       "memory": int(rng.integers(0, 3000))},
            usage={"cpu": int(rng.integers(0, 6000)),
                   "memory": int(rng.integers(0, 6000))},
            metric_fresh=bool(rng.random() > 0.15),
            accel_type=int(rng.integers(0, accels)),
        )
        for i in range(n_nodes)
    ]
    gangs = [dict(name="g0", min_member=2), dict(name="g1", min_member=3)]
    quotas = [
        dict(name="q0",
             runtime={"cpu": 40000, "memory": 80000},
             used={"cpu": int(rng.integers(0, 8000))}),
        dict(name="q1",
             runtime={"cpu": 20000, "memory": 30000},
             used={"cpu": int(rng.integers(0, 8000))}),
    ]
    pods = [
        dict(
            name=f"p{i}",
            requests={"cpu": int(rng.integers(100, 3000)),
                      "memory": int(rng.integers(128, 3000))},
            priority=int(rng.integers(3000, 9999)),
            gang=(
                ["g0", "g1"][int(rng.integers(0, 2))]
                if rng.random() > 0.6 else None
            ),
            quota=(
                ["q0", "q1"][int(rng.integers(0, 2))]
                if rng.random() > 0.4 else None
            ),
            workload_class=int(rng.integers(0, classes)),
            sensitivity={"cpu": int(rng.integers(0, 101)),
                         "memory": int(rng.integers(0, 101))},
        )
        for i in range(n_pods)
    ]
    tput = rng.integers(0, 101, (classes, accels)).astype(np.int64)
    return nodes, pods, gangs, quotas, tput


def _term_snapshot(seed, n_nodes=9, n_pods=14):
    rng = np.random.default_rng(seed)
    nodes, pods, gangs, quotas, tput = _term_lists(rng, n_nodes, n_pods)
    return encode_snapshot(nodes, pods, gangs, quotas, throughput=tput)


class TestNumpyOracleParity:
    @pytest.mark.parametrize("seed", range(4))
    @pytest.mark.parametrize(
        "term", ["heterogeneity", "sensitivity", "packing", "all"]
    )
    def test_fused_term_matches_numpy_oracle(self, seed, term):
        snap = _term_snapshot(seed)
        cfg = _cfg_for(term)
        s, f = map(np.asarray, score_cycle(snap, cfg))
        s0, f0 = map(np.asarray, score_cycle(snap, CycleConfig()))
        xs, xm = np_term_tensors(snap, cfg)
        np.testing.assert_array_equal(s, s0 + xs)
        np.testing.assert_array_equal(f, f0 & xm)

    def test_term_extras_match_numpy_oracle(self):
        snap = _term_snapshot(0)
        xs, xm = term_extras(snap, ALL_TERMS)
        ns, nm = np_term_tensors(snap, ALL_TERMS)
        np.testing.assert_array_equal(np.asarray(xs), ns)
        np.testing.assert_array_equal(np.asarray(xm), nm)

    def test_missing_term_data_is_inert(self):
        # terms enabled but NO term tensors synced: the cycle must not
        # fault and must score exactly like the untermed config (the
        # packing term needs no side tensors, so exclude it)
        rng = np.random.default_rng(3)
        nodes, pods, gangs, quotas, _ = _term_lists(rng, 6, 8)
        for nd in nodes:
            nd.pop("accel_type")
        for pd in pods:
            pd.pop("workload_class")
            pd.pop("sensitivity")
        snap = encode_snapshot(nodes, pods, gangs, quotas)
        cfg = CycleConfig(
            heterogeneity=HeterogeneityTermArgs(),
            sensitivity=SensitivityTermArgs(),
        )
        s, f = map(np.asarray, score_cycle(snap, cfg))
        s0, f0 = map(np.asarray, score_cycle(snap, CycleConfig()))
        np.testing.assert_array_equal(s, s0)
        np.testing.assert_array_equal(f, f0)


class TestAssignWaveParity:
    @pytest.mark.parametrize("seed", range(3))
    @pytest.mark.parametrize("wave", [1, 8, 32])
    def test_run_cycle_terms_equal_oracle_extras(self, seed, wave):
        snap = _term_snapshot(seed, n_nodes=10, n_pods=18)
        cfg = dataclasses.replace(ALL_TERMS, wave=wave)
        got = run_cycle(snap, cfg)
        xs, xm = np_term_tensors(snap, ALL_TERMS)
        want = greedy_assign(
            snap, CycleConfig(),
            extra_mask=jnp.asarray(xm), extra_scores=jnp.asarray(xs),
        )
        np.testing.assert_array_equal(
            np.asarray(got.assignment), np.asarray(want.assignment)
        )
        np.testing.assert_array_equal(
            np.asarray(got.status), np.asarray(want.status)
        )


# ---------------------------------------------------------------------------
# servicer streams: mesh parity + dirty attribution + retrace guard
# ---------------------------------------------------------------------------


def _full_term_sync(state) -> "pb2.SyncRequest":
    req = pb2.SyncRequest()
    req.nodes.allocatable.CopyFrom(numpy_to_tensor(state["nalloc"]))
    req.nodes.requested.CopyFrom(numpy_to_tensor(state["nreq"]))
    req.nodes.usage.CopyFrom(numpy_to_tensor(state["nuse"]))
    req.nodes.metric_fresh.extend(bool(b) for b in state["fresh"])
    req.nodes.accel_type.extend(int(v) for v in state["accel"])
    req.pods.requests.CopyFrom(numpy_to_tensor(state["preq"]))
    req.pods.estimated.CopyFrom(numpy_to_tensor(state["pest"]))
    req.pods.priority.extend(int(v) for v in state["prio"])
    req.pods.gang_id.extend(int(v) for v in state["gang"])
    req.pods.quota_id.extend(int(v) for v in state["quota"])
    req.pods.workload_class.extend(int(v) for v in state["wclass"])
    req.pods.sensitivity.CopyFrom(numpy_to_tensor(state["sens"]))
    req.gangs.min_member.extend([2, 3])
    req.quotas.runtime.CopyFrom(numpy_to_tensor(state["qrt"]))
    req.quotas.used.CopyFrom(numpy_to_tensor(state["quse"]))
    req.quotas.limited.CopyFrom(numpy_to_tensor(state["qlim"]))
    req.terms.throughput.CopyFrom(numpy_to_tensor(state["tput"]))
    return req


def _term_state(rng, n_nodes=8, n_pods=12, classes=3, accels=2):
    sens = np.zeros((n_pods, R), np.int64)
    sens[:, 0] = rng.integers(0, 101, n_pods)
    sens[:, 1] = rng.integers(0, 101, n_pods)
    return {
        "nalloc": rng.integers(4000, 64000, (n_nodes, R)).astype(np.int64),
        "nreq": rng.integers(0, 2000, (n_nodes, R)).astype(np.int64),
        "nuse": rng.integers(0, 3000, (n_nodes, R)).astype(np.int64),
        "fresh": rng.random(n_nodes) > 0.2,
        "accel": (np.arange(n_nodes) % accels).astype(np.int64),
        "preq": rng.integers(1, 4000, (n_pods, R)).astype(np.int64),
        "pest": rng.integers(1, 4000, (n_pods, R)).astype(np.int64),
        "prio": rng.integers(0, 9999, n_pods).astype(np.int64),
        "gang": np.where(
            rng.random(n_pods) > 0.5, rng.integers(0, 2, n_pods), -1
        ).astype(np.int64),
        "quota": np.where(
            rng.random(n_pods) > 0.4, rng.integers(0, 2, n_pods), -1
        ).astype(np.int64),
        "wclass": rng.integers(0, classes, n_pods).astype(np.int64),
        "sens": sens,
        "qrt": rng.integers(5000, 500000, (2, R)).astype(np.int64),
        "quse": rng.integers(0, 4000, (2, R)).astype(np.int64),
        "qlim": (rng.random((2, R)) > 0.5).astype(np.int64),
        "tput": rng.integers(0, 101, (classes, accels)).astype(np.int64),
    }


def _flat(sv, k=8):
    return sv.score(pb2.ScoreRequest(
        snapshot_id=sv.snapshot_id(), top_k=k, flat=True
    )).flat.SerializeToString()


def _term_mutations(rng, state):
    """One warm term-touching mutation; returns the delta SyncRequest."""
    req = pb2.SyncRequest()
    kind = int(rng.integers(0, 4))
    if kind == 0:  # sensitivity drift
        prev = state["sens"].copy()
        rows = rng.choice(
            state["sens"].shape[0], int(rng.integers(1, 4)), replace=False
        )
        for r_ in rows:
            state["sens"][r_, 0] = int(rng.integers(0, 101))
        req.pods.sensitivity.CopyFrom(
            numpy_to_tensor(state["sens"], prev)
        )
    elif kind == 1:  # throughput-matrix update (one (class, accel) cell
        # — dirt stays the one accel type's node columns, under the
        # incremental engine's dirty-ratio gate)
        prev = state["tput"].copy()
        c = int(rng.integers(0, state["tput"].shape[0]))
        a = int(rng.integers(0, state["tput"].shape[1]))
        state["tput"][c, a] = int(rng.integers(0, 101))
        req.terms.throughput.CopyFrom(
            numpy_to_tensor(state["tput"], prev)
        )
    elif kind == 2:  # accel-type flip
        n = int(rng.integers(0, len(state["accel"])))
        state["accel"][n] = (state["accel"][n] + 1) % 2
        req.nodes.accel_type.extend(int(v) for v in state["accel"])
    else:  # workload-class flip + a usage tick (mixed frame)
        p = int(rng.integers(0, len(state["wclass"])))
        state["wclass"][p] = (state["wclass"][p] + 1) % 3
        req.pods.workload_class.extend(int(v) for v in state["wclass"])
        prev = state["nuse"].copy()
        state["nuse"][int(rng.integers(0, len(state["fresh"]))), 0] += 7
        req.nodes.usage.CopyFrom(numpy_to_tensor(state["nuse"], prev))
    return req


class TestMeshParity:
    @pytest.mark.parametrize("devices", [1, 8])
    @pytest.mark.parametrize("seed", range(2))
    def test_warm_term_stream_mesh_vs_single_chip(self, devices, seed):
        from koordinator_tpu.parallel import cluster_mesh

        rng = np.random.default_rng(seed)
        state = _term_state(rng)
        cfg = ALL_TERMS
        mesh_kw = {}
        if devices > 1:
            mesh_kw = dict(
                mesh=cluster_mesh(jax.devices()[:devices]),
                mesh_resident=True,
            )
        sharded = ScorerServicer(cfg=cfg, score_memo=False, **mesh_kw)
        oracle = ScorerServicer(
            cfg=cfg, score_memo=False, score_incr=False
        )
        raw = _full_term_sync(state).SerializeToString()
        for sv in (sharded, oracle):
            sv.sync(pb2.SyncRequest.FromString(raw))
        assert _flat(sharded) == _flat(oracle)
        for _ in range(6):
            raw = _term_mutations(rng, state).SerializeToString()
            for sv in (sharded, oracle):
                sv.sync(pb2.SyncRequest.FromString(raw))
                assert sv.state.last_sync_path == "warm"
            assert _flat(sharded) == _flat(oracle)
        # the engine actually rescored incrementally (not full fallback)
        incr = sharded.telemetry.registry.get(
            "koord_scorer_score_incr_total", {"result": "incr"}
        ) or 0
        assert incr >= 4


class TestDirtyAttribution:
    def _pair(self, seed=0):
        rng = np.random.default_rng(seed)
        state = _term_state(rng)
        incr = ScorerServicer(cfg=ALL_TERMS, score_memo=False)
        full = ScorerServicer(
            cfg=ALL_TERMS, score_memo=False, score_incr=False
        )
        raw = _full_term_sync(state).SerializeToString()
        for sv in (incr, full):
            sv.sync(pb2.SyncRequest.FromString(raw))
        assert _flat(incr) == _flat(full)
        return state, incr, full

    def _warm(self, state_req, incr, full):
        raw = state_req.SerializeToString()
        for sv in (incr, full):
            sv.sync(pb2.SyncRequest.FromString(raw))
            assert sv.state.last_sync_path == "warm"

    def test_sensitivity_delta_dirties_exactly_touched_pods(self):
        state, incr, full = self._pair()
        prev = state["sens"].copy()
        state["sens"][3, 0] += 9
        state["sens"][5, 1] += 4
        req = pb2.SyncRequest()
        req.pods.sensitivity.CopyFrom(numpy_to_tensor(state["sens"], prev))
        self._warm(req, incr, full)
        res_st = incr.state.score_residency()
        assert res_st.dirty_pods == {3, 5}
        assert res_st.dirty_nodes == set()
        assert _flat(incr) == _flat(full)

    def test_throughput_delta_dirties_only_matching_accel_nodes(self):
        state, incr, full = self._pair(1)
        prev = state["tput"].copy()
        state["tput"][1, 1] += 5  # accel type 1's column
        req = pb2.SyncRequest()
        req.terms.throughput.CopyFrom(numpy_to_tensor(state["tput"], prev))
        self._warm(req, incr, full)
        res_st = incr.state.score_residency()
        want = set(np.flatnonzero(state["accel"] == 1).tolist())
        assert res_st.dirty_nodes == want
        assert res_st.dirty_pods == set()
        assert _flat(incr) == _flat(full)

    def test_accel_and_workload_flips_dirty_their_rows(self):
        state, incr, full = self._pair(2)
        state["accel"][2] = (state["accel"][2] + 1) % 2
        req = pb2.SyncRequest()
        req.nodes.accel_type.extend(int(v) for v in state["accel"])
        self._warm(req, incr, full)
        assert incr.state.score_residency().dirty_nodes == {2}
        assert _flat(incr) == _flat(full)
        state["wclass"][4] = (state["wclass"][4] + 1) % 3
        req = pb2.SyncRequest()
        req.pods.workload_class.extend(int(v) for v in state["wclass"])
        self._warm(req, incr, full)
        assert incr.state.score_residency().dirty_pods == {4}
        assert _flat(incr) == _flat(full)

    def test_warm_term_stream_holds_zero_jit_misses(self):
        rng = np.random.default_rng(7)
        state, incr, full = self._pair(7)
        # warm-up: one mutation of each kind compiles every bucket
        for kind_seed in range(4):
            self._warm(_term_mutations(
                np.random.default_rng(100 + kind_seed), state
            ), incr, full)
            assert _flat(incr) == _flat(full)
        with retrace_guard(budget=0):
            for _ in range(6):
                self._warm(_term_mutations(rng, state), incr, full)
                assert _flat(incr) == _flat(full)

    def test_first_term_column_appearance_goes_cold(self):
        # a snapshot synced WITHOUT accel gaining it later changes the
        # resident pytree structure: the commit must drop residency
        # (cold) instead of warm-patching a None leaf
        rng = np.random.default_rng(9)
        state = _term_state(rng)
        req = _full_term_sync(state)
        req.nodes.ClearField("accel_type")
        sv = ScorerServicer(cfg=ALL_TERMS, score_memo=False)
        sv.sync(req)
        _flat(sv)
        late = pb2.SyncRequest()
        late.nodes.accel_type.extend(int(v) for v in state["accel"])
        sv.sync(late)
        assert sv.state.last_sync_path == "cold"


class TestServingBound:
    @pytest.mark.parametrize("seed", range(3))
    def test_scores_stay_under_term_aware_bound(self, seed):
        snap = _term_snapshot(seed)
        s, f = map(np.asarray, score_cycle(snap, ALL_TERMS))
        hi = score_upper_bound(ALL_TERMS)
        assert hi == score_upper_bound(CycleConfig()) + terms_upper_bound(
            ALL_TERMS
        )
        assert s[f].max(initial=0) <= hi
        assert s[f].min(initial=0) >= 0

    @pytest.mark.parametrize("seed", range(3))
    def test_masked_top_k_fast_path_exact_with_terms(self, seed):
        snap = _term_snapshot(seed)
        s, f = score_cycle(snap, ALL_TERMS)
        k = 6
        ts, ti = masked_top_k(s, f, k=k, hi=score_upper_bound(ALL_TERMS))
        masked = jnp.where(f, s, jnp.iinfo(jnp.int64).min)
        want_ts, want_ti = lax.top_k(masked, k)
        np.testing.assert_array_equal(np.asarray(ts), np.asarray(want_ts))
        np.testing.assert_array_equal(np.asarray(ti), np.asarray(want_ti))

    @pytest.mark.parametrize("seed", range(3))
    def test_masked_top_k_host_bit_identical(self, seed):
        snap = _term_snapshot(seed)
        s, f = score_cycle(snap, ALL_TERMS)
        for k in (1, 5, int(np.asarray(s).shape[1])):
            ts, ti = masked_top_k(
                s, f, k=k, hi=score_upper_bound(ALL_TERMS)
            )
            hts, hti = masked_top_k_host(np.asarray(s), np.asarray(f), k)
            np.testing.assert_array_equal(hts, np.asarray(ts))
            np.testing.assert_array_equal(hti, np.asarray(ti))

    def test_masked_top_k_host_extreme_values(self):
        # ties break toward the lower index; i64 extremes must not
        # overflow the host ranking (the uint64-bias trick)
        s = np.asarray([[5, 5, np.iinfo(np.int64).max,
                         np.iinfo(np.int64).min, 5]], np.int64)
        f = np.asarray([[True, True, True, True, False]])
        ts, ti = masked_top_k_host(s, f, 4)
        assert ti.tolist() == [[2, 0, 1, 3]]
        dts, dti = lax.top_k(
            jnp.where(jnp.asarray(f), jnp.asarray(s),
                      jnp.iinfo(jnp.int64).min), 4
        )
        np.testing.assert_array_equal(ts, np.asarray(dts))
        np.testing.assert_array_equal(ti, np.asarray(dti))


class TestTermConfigSurface:
    def test_term_configs_hash_and_freeze(self):
        a = PackingTermArgs(headroom={"cpu": 90, "memory": 95})
        b = PackingTermArgs(
            headroom=(("cpu", 90), ("memory", 95))
        )
        assert a == b and hash(a) == hash(b)
        assert hash(ALL_TERMS) == hash(
            default_term_config(packing_headroom=HEADROOM)
        )

    def test_term_names_and_bounds(self):
        assert term_names(CycleConfig()) == ()
        assert term_names(ALL_TERMS) == (
            "heterogeneity", "sensitivity", "packing"
        )
        assert terms_upper_bound(CycleConfig()) == 0
        assert terms_upper_bound(ALL_TERMS) == 3 * MAX_NODE_SCORE

    def test_term_metric_counts_per_launch(self):
        rng = np.random.default_rng(11)
        state = _term_state(rng)
        sv = ScorerServicer(cfg=ALL_TERMS, score_memo=False)
        sv.sync(_full_term_sync(state))
        _flat(sv)
        reg = sv.telemetry.registry
        for term in ("heterogeneity", "sensitivity", "packing"):
            assert reg.get(
                "koord_scorer_term_total", {"term": term}
            ) == 1.0


class TestTermTraceEvents:
    def _cfg(self, seed=5):
        from koordinator_tpu.harness.trace import TERM_MIX, TraceConfig

        return TraceConfig(
            seed=seed, nodes=6, pod_slots=24, gangs=2, gang_min_member=3,
            events=8, mix=TERM_MIX, accel_types=2, workload_classes=3,
        )

    def test_term_trace_digest_pinned_per_seed(self):
        from koordinator_tpu.harness.trace import generate_trace

        a = generate_trace(self._cfg())
        assert a.digest() == generate_trace(self._cfg()).digest()
        assert a.digest() != generate_trace(self._cfg(seed=6)).digest()
        kinds = {e.kind for e in a.events}
        assert kinds & {"throughput_update", "sensitivity_drift"}

    def test_term_trace_export_import_round_trip(self):
        from koordinator_tpu.harness.trace import (
            export_trace,
            generate_trace,
            import_trace,
        )

        trace = generate_trace(self._cfg())
        rebuilt = import_trace(export_trace(trace))
        assert rebuilt.digest() == trace.digest()

    def test_term_trace_replays_with_parity_and_zero_retraces(self):
        from koordinator_tpu.harness.trace import TraceReplay, generate_trace

        trace = generate_trace(self._cfg())
        cfg = default_term_config(packing_headroom=HEADROOM)
        # no explicit oracle_kw: TraceReplay defaults the oracle's cfg
        # from engine_kw, so term-enabled replays are parity-consistent
        # out of the box
        report = TraceReplay(trace, engine_kw=dict(cfg=cfg)).run()
        assert report.retraces == 0
        assert report.parity_checks == len(trace.events) + 1

    def test_chaos_trace_gate_runs_with_terms(self, tmp_path):
        # the chaos x trace gate (ISSUE 13) exercises the new terms on
        # the warm delta path: a launch-failure burst mid-replay over a
        # TERM_MIX trace still converges to oracle parity with the
        # three-term config on BOTH sides
        from koordinator_tpu.harness.chaos import ChaosTraceReplay
        from koordinator_tpu.harness.trace import generate_trace

        trace = generate_trace(self._cfg())
        cfg = default_term_config(packing_headroom=HEADROOM)
        report = ChaosTraceReplay(
            trace, str(tmp_path), fail_at=2, fail_n=4,
            servicer_kw=dict(cfg=cfg),
        ).run()
        assert report.parity_ok, report.parity_detail
        assert report.events_replayed == len(trace.events)
        assert report.breaker_trips >= 1
