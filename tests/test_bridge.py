"""BatchedScorer sidecar: UDS round trip, delta sync, parity with the
in-process cycle (the bridge must be a transparent seam)."""

import os
import tempfile

import numpy as np
import pytest

from koordinator_tpu.bridge import ScorerClient, serve_uds
from koordinator_tpu.bridge.state import numpy_to_tensor, tensor_to_numpy
from koordinator_tpu.harness import generators
from koordinator_tpu.model import encode_snapshot, resources as res
from koordinator_tpu.model.snapshot import PriorityClass, estimate_pod
from koordinator_tpu.solver import run_cycle


@pytest.fixture()
def bridge():
    sock = os.path.join(tempfile.mkdtemp(), "scorer.sock")
    server = serve_uds(sock)
    client = ScorerClient(f"unix://{sock}")
    yield client
    client.close()
    server.stop(0)


def _tables(pods=24, nodes=6):
    nodes_l, pods_l, gangs, _ = generators.loadaware_joint(
        seed=11, pods=pods, nodes=nodes
    )
    nalloc = np.asarray([res.resource_vector(n["allocatable"]) for n in nodes_l])
    nuse = np.asarray([res.resource_vector(n.get("usage", {})) for n in nodes_l])
    preq = np.asarray([res.resource_vector(p["requests"]) for p in pods_l])
    # the client owns the estimator (the host scheduler computes estimates;
    # the sidecar scores whatever it is given) — mirror encode_snapshot
    pest = np.asarray(
        [
            estimate_pod(
                res.resource_vector(p["requests"]),
                res.resource_vector(p.get("limits", {})),
                PriorityClass.from_name(p.get("priority_class"))
                if p.get("priority_class") is not None
                else PriorityClass.from_priority_value(p.get("priority")),
            )
            for p in pods_l
        ]
    )
    return nodes_l, pods_l, gangs, nalloc, nuse, preq, pest


class TestBridge:
    def test_sync_assign_matches_inprocess(self, bridge):
        nodes_l, pods_l, gangs, nalloc, nuse, preq, pest = _tables()
        reply = bridge.sync(
            node_allocatable=nalloc,
            node_requested=np.zeros_like(nalloc),
            node_usage=nuse,
            node_names=[n["name"] for n in nodes_l],
            pod_requests=preq,
            pod_estimated=pest,
            pod_names=[p["name"] for p in pods_l],
            priority=[p.get("priority", 0) for p in pods_l],
        )
        assert reply.nodes == len(nodes_l) and reply.pods == len(pods_l)
        assignment, status, ms, path = bridge.assign()
        assert len(assignment) == len(pods_l)
        assert ms > 0
        # degraded-path visibility: the reply must name the device program
        assert path in ("pallas", "scan", "shard")

        # parity: the same cluster through the in-process entry point
        snap = encode_snapshot(
            [{**n, "requested": {}} for n in nodes_l], list(pods_l), [], []
        )
        direct = run_cycle(snap)
        direct_assign = np.asarray(direct.assignment)[: len(pods_l)]
        np.testing.assert_array_equal(assignment, direct_assign)

    def test_score_topk_sorted_and_feasible(self, bridge):
        nodes_l, pods_l, gangs, nalloc, nuse, preq, pest = _tables()
        bridge.sync(
            node_allocatable=nalloc,
            node_requested=np.zeros_like(nalloc),
            node_usage=nuse,
            pod_requests=preq,
            pod_estimated=pest,
        )
        lists = bridge.score(top_k=3)
        assert lists and all(len(entry) <= 3 for entry in lists)
        for entry in lists:
            scores = [s for _, s in entry]
            assert scores == sorted(scores, reverse=True)

    def test_delta_sync_updates_usage(self, bridge):
        nodes_l, pods_l, gangs, nalloc, nuse, preq, pest = _tables()
        bridge.sync(
            node_allocatable=nalloc,
            node_requested=np.zeros_like(nalloc),
            node_usage=nuse,
            pod_requests=preq,
            pod_estimated=pest,
        )
        a1, _, _, _ = bridge.assign()
        # warm cycle: bump usage on one node; client auto-encodes a delta
        nuse2 = nuse.copy()
        nuse2[0, res.RESOURCE_INDEX[res.CPU]] += 1000
        reply = bridge.sync(
            node_usage=nuse2,
            pod_requests=preq,
            pod_estimated=pest,
        )
        assert reply.nodes == len(nodes_l)
        a2, _, _, _ = bridge.assign()
        assert len(a2) == len(a1)

    def test_tensor_delta_roundtrip(self):
        prev = np.arange(64, dtype=np.int64).reshape(8, 8)
        nxt = prev.copy()
        nxt[3, 4] = 999
        t = numpy_to_tensor(nxt, prev)
        assert t.delta_idx and not t.data  # shipped as sparse delta
        back = tensor_to_numpy(t, prev)
        np.testing.assert_array_equal(back, nxt)

    def test_delta_out_of_range_raises_not_corrupts(self):
        """Client deltas cross a trust boundary: an index past the
        resident tensor must raise (the native delta_apply writes through
        raw pointers — unchecked it would corrupt server memory, not
        error)."""
        import pytest

        from koordinator_tpu.bridge.codegen import pb2

        base = np.zeros((2, 4), np.int64)
        t = pb2.Tensor(shape=[2, 4])
        t.delta_idx = np.asarray([99], "<i8").tobytes()
        t.delta_val = np.asarray([7], "<i8").tobytes()
        with pytest.raises(ValueError, match="out of range"):
            tensor_to_numpy(t, base)
        t.delta_idx = np.asarray([1, 2], "<i8").tobytes()
        with pytest.raises(ValueError, match="length mismatch"):
            tensor_to_numpy(t, base)

    def test_delta_shape_mismatch_rejected(self):
        """A stale differently-shaped mirror's indices may all land
        inside the resident cell count but write the wrong cells — shape
        equality must reject the frame outright."""
        import pytest

        from koordinator_tpu.bridge.codegen import pb2

        base = np.zeros((12, 13), np.int64)
        t = pb2.Tensor(shape=[8, 13])
        t.delta_idx = np.asarray([5], "<i8").tobytes()
        t.delta_val = np.asarray([7], "<i8").tobytes()
        with pytest.raises(ValueError, match="delta shape"):
            tensor_to_numpy(t, base)

    def test_rejected_sync_leaves_resident_state_untouched(self):
        """Half-applied syncs must not happen: a frame whose first
        tensor is valid but whose later tensor is rejected leaves the
        OTHER clients' delta baseline corrupted behind an unbumped
        generation.  apply_sync stages everything and commits only when
        the whole frame decodes."""
        import pytest

        from koordinator_tpu.bridge.codegen import pb2
        from koordinator_tpu.bridge.server import ScorerServicer
        from koordinator_tpu.harness import generators
        from koordinator_tpu.harness.golden import build_sync_request

        nodes_l, pods_l, _, _ = generators.loadaware_joint(
            seed=5, pods=16, nodes=4
        )
        req, _ = build_sync_request(nodes_l, pods_l, [], [])
        sv = ScorerServicer()
        sv.sync(req)
        before = sv.state.node_alloc.copy()

        bad = pb2.SyncRequest()
        # valid full allocatable replacement...
        bad.nodes.allocatable.shape.extend(before.shape)
        bad.nodes.allocatable.data = (before * 2).astype("<i8").tobytes()
        # ...but an out-of-range usage delta: the whole frame must bounce
        bad.nodes.usage.shape.extend(before.shape)
        bad.nodes.usage.delta_idx = np.asarray([10**6], "<i8").tobytes()
        bad.nodes.usage.delta_val = np.asarray([1], "<i8").tobytes()
        with pytest.raises(ValueError):
            sv.state.apply_sync(bad)
        np.testing.assert_array_equal(sv.state.node_alloc, before)

    def test_tensor_full_when_mostly_changed(self):
        prev = np.zeros((8, 8), np.int64)
        nxt = np.arange(64, dtype=np.int64).reshape(8, 8)
        t = numpy_to_tensor(nxt, prev)
        assert t.data and not t.delta_idx
        np.testing.assert_array_equal(tensor_to_numpy(t, None), nxt)


class TestFlatScoreReply:
    def test_flat_matches_legacy_lists(self):
        from koordinator_tpu.bridge.server import ScorerServicer
        from koordinator_tpu.harness.golden import build_sync_request
        from koordinator_tpu.harness import generators
        import numpy as np

        n, p, g, q = generators.loadaware_joint(seed=9, pods=64, nodes=16)
        req, _ = build_sync_request(n, p, [], [], node_bucket=16, pod_bucket=64)
        sv = ScorerServicer()
        sid = sv.sync(req).snapshot_id
        from koordinator_tpu.bridge.codegen import pb2

        legacy = sv.score(pb2.ScoreRequest(snapshot_id=sid, top_k=4))
        flat = sv.score(pb2.ScoreRequest(snapshot_id=sid, top_k=4, flat=True))
        pods_idx = np.frombuffer(flat.flat.pod_index, "<i4")
        counts = np.frombuffer(flat.flat.counts, "<i4")
        nidx = np.frombuffer(flat.flat.node_index, "<i4")
        scores = np.frombuffer(flat.flat.score, "<i8")
        assert counts.sum() == len(nidx) == len(scores)
        assert len(pods_idx) == len(legacy.pods)
        off = 0
        for entry, c in zip(legacy.pods, counts):
            assert list(entry.node_index) == nidx[off : off + c].tolist()
            assert list(entry.score) == scores[off : off + c].tolist()
            off += c
        assert flat.build_ms >= 0.0 and not flat.pods


class TestMultiChipServing:
    def test_mesh_backed_assign_matches_single_chip(self, tmp_path):
        """The production seam serves the round-based multi-chip cycle:
        a mesh-backed sidecar reports path="shard" and places pods
        bit-identically with a single-chip sidecar fed the same sync."""
        import jax

        if len(jax.devices()) < 8:
            import pytest

            pytest.skip("needs 8 (virtual) devices")
        from koordinator_tpu.bridge.codegen import pb2
        from koordinator_tpu.bridge.server import ScorerServicer
        from koordinator_tpu.harness.golden import build_sync_request
        from koordinator_tpu.parallel import make_mesh

        nodes_l, pods_l, _, _ = generators.loadaware_joint(
            seed=21, pods=128, nodes=32
        )
        req, _ = build_sync_request(nodes_l, pods_l, [], [])

        sharded = ScorerServicer(mesh=make_mesh(jax.devices()[:8]))
        sid = sharded.sync(req).snapshot_id
        shard_reply = sharded.assign(pb2.AssignRequest(snapshot_id=sid))
        assert shard_reply.path == "shard"

        single = ScorerServicer()
        sid = single.sync(req).snapshot_id
        single_reply = single.assign(pb2.AssignRequest(snapshot_id=sid))
        assert list(shard_reply.assignment) == list(single_reply.assignment)
        assert list(shard_reply.status) == list(single_reply.status)

        # a 1-device mesh is honored too (path="shard", not silently
        # dropped): a dev box or degraded slice keeps the contract
        one = ScorerServicer(mesh=make_mesh(jax.devices()[:1]))
        sid = one.sync(req).snapshot_id
        one_reply = one.assign(pb2.AssignRequest(snapshot_id=sid))
        assert one_reply.path == "shard"
        assert list(one_reply.assignment) == list(single_reply.assignment)

    def test_shard_fault_falls_back_and_demotes(self, tmp_path, monkeypatch):
        """A shard-path fault serves the RPC single-chip (bit-identical)
        and demotes the shape bucket so later RPCs skip the failing
        shard attempt instead of re-paying it (the run_cycle demotion
        machinery, shared)."""
        import jax

        if len(jax.devices()) < 8:
            import pytest

            pytest.skip("needs 8 (virtual) devices")
        import koordinator_tpu.parallel as parallel
        from koordinator_tpu.bridge.codegen import pb2
        from koordinator_tpu.bridge.server import ScorerServicer
        from koordinator_tpu.harness.golden import build_sync_request
        from koordinator_tpu.parallel import make_mesh

        nodes_l, pods_l, _, _ = generators.loadaware_joint(
            seed=4, pods=32, nodes=8
        )
        req, _ = build_sync_request(nodes_l, pods_l, [], [])
        sv = ScorerServicer(mesh=make_mesh(jax.devices()[:8]))
        sid = sv.sync(req).snapshot_id

        calls = {"n": 0}

        def boom(*a, **k):
            calls["n"] += 1
            raise RuntimeError("wedged device")

        monkeypatch.setattr(parallel, "greedy_assign_waves", boom)
        try:
            r1 = sv.assign(pb2.AssignRequest(snapshot_id=sid))
            assert r1.path in ("scan", "pallas", "dense")  # single-chip
            assert calls["n"] == 1
            # demoted: the next RPC skips the failing shard path
            r2 = sv.assign(pb2.AssignRequest(snapshot_id=sid))
            assert calls["n"] == 1
            assert list(r2.assignment) == list(r1.assignment)
        finally:
            # the demotion store is process-global (pallas_demotions()
            # returns a snapshot copy); drop this test's bucket from the
            # live store so exact-count assertions elsewhere stay true
            from koordinator_tpu import solver

            with solver._PALLAS_LOCK:
                for bucket in [
                    b for b in solver._PALLAS_FAILURES if b[0] == "shard"
                ]:
                    solver._PALLAS_FAILURES.pop(bucket, None)


class TestRawUdsReplyCap:
    def test_oversized_reply_errors_and_conn_survives(self, tmp_path, monkeypatch):
        """The server must refuse replies over the transport cap with a
        real error (every client enforces the same cap and would reject
        the frame as 'reply frame exceeds cap' otherwise) and keep the
        connection serving."""
        import socket
        import struct

        from koordinator_tpu.bridge import udsserver
        from koordinator_tpu.bridge.codegen import pb2
        from koordinator_tpu.harness import generators
        from koordinator_tpu.harness.golden import build_sync_request

        nodes_l, pods_l, _, _ = generators.loadaware_joint(
            seed=3, pods=32, nodes=8
        )
        req, _ = build_sync_request(nodes_l, pods_l, [], [])
        sock_path = str(tmp_path / "scorer.sock")
        server = udsserver.RawUdsServer(sock_path).start()

        def call(conn, method, payload):
            conn.sendall(struct.pack(">BI", method, len(payload)) + payload)
            head = conn.recv(5, socket.MSG_WAITALL)
            status, length = struct.unpack(">BI", head)
            body = b""
            while len(body) < length:
                body += conn.recv(length - len(body))
            return status, body

        try:
            c = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            c.connect(sock_path)
            status, body = call(c, 1, req.SerializeToString())
            assert status == 0
            sid = pb2.SyncReply.FromString(body).snapshot_id
            # shrink the cap below any full-matrix Score reply
            monkeypatch.setattr(udsserver, "_MAX_FRAME", 64)
            score = pb2.ScoreRequest(snapshot_id=sid, top_k=0, flat=True)
            status, body = call(c, 2, score.SerializeToString())
            assert status == 1 and b"exceeds" in body
            # the connection is still serving after the refusal
            monkeypatch.setattr(udsserver, "_MAX_FRAME", 64 << 20)
            status, _ = call(c, 2, pb2.ScoreRequest(
                snapshot_id=sid, top_k=2, flat=True
            ).SerializeToString())
            assert status == 0
            c.close()
        finally:
            server.stop()
