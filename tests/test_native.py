"""Native shim library: build, batched reads, delta codec, perf fallback.

Reference native boundary: cgo libpfm4 perf groups
(pkg/koordlet/util/perf_group/perf_group_linux.go); the delta codec backs
SURVEY §7's host->device transfer trimming.
"""

import os

import numpy as np
import pytest

from koordinator_tpu import native


class TestLibrary:
    def test_builds_and_loads(self):
        # the build toolchain is part of the runtime contract; if this
        # fails the collectors silently lose their native fast path
        assert native.available()

    def test_read_files_batch(self, tmp_path):
        for i in range(5):
            (tmp_path / f"f{i}").write_text(f"value-{i}\n")
        paths = [str(tmp_path / f"f{i}") for i in range(5)] + ["/no/such/file"]
        got = native.read_files(paths)
        assert got[:5] == [f"value-{i}\n" for i in range(5)]
        assert got[5] is None

    def test_delta_roundtrip(self):
        rng = np.random.default_rng(0)
        prev = rng.integers(0, 1000, size=(64, 13)).astype(np.int64)
        nxt = prev.copy()
        nxt[rng.integers(0, 64, 20), rng.integers(0, 13, 20)] += 7
        idx, val = native.delta_encode(prev, nxt)
        base = prev.copy()
        native.delta_apply(base, idx, val)
        assert (base == nxt).all()

    def test_delta_cap_falls_back(self):
        prev = np.zeros(100, np.int64)
        nxt = np.ones(100, np.int64)
        assert native.delta_encode(prev, nxt, max_changes=10) is None

    def test_delta_empty(self):
        a = np.arange(10, dtype=np.int64)
        idx, val = native.delta_encode(a, a)
        assert len(idx) == 0

    def test_perf_graceful(self):
        # perf_event_open is usually fenced off in CI containers; the API
        # must degrade to None, never crash (the reference gates CPI
        # collection behind a feature gate the same way)
        got = native.read_self_cpi()
        assert got is None or (got[0] > 0 and got[1] > 0)


class TestPerfSingleReader:
    def test_single_event_reader_monotonic(self):
        """Non-grouped perf reader (reference pkg/koordlet/util/perf/):
        a software task-clock counter on self must be monotonic."""
        from koordinator_tpu.native import (
            PERF_COUNT_SW_TASK_CLOCK,
            PERF_TYPE_SOFTWARE,
            PerfSingleReader,
        )

        try:
            r = PerfSingleReader(0, PERF_TYPE_SOFTWARE, PERF_COUNT_SW_TASK_CLOCK)
        except OSError:
            pytest.skip("perf_event_open unavailable in this sandbox")
        try:
            v1 = r.read()
            for _ in range(10000):
                pass
            v2 = r.read()
            assert v2 >= v1 >= 0
        finally:
            r.close()
