"""DeviceShare: request normalization, multi-card split, fit, score, minors.

Reference semantics: pkg/scheduler/plugins/deviceshare
(device_cache.go calcDeviceWanted/tryAllocateByDeviceType,
utils.go fillGPUTotalMem, scoring.go scoreNode, device_resources.go sort).
"""

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.model import resources as res
from koordinator_tpu.model.device import encode_devices
from koordinator_tpu.ops.deviceshare import (
    allocate_minors,
    device_fit_mask,
    deviceshare_scores,
    gpu_card_total_memory,
    normalize_gpu_requests,
    pod_device_requests,
    split_per_card,
)

GI = 1024**3
GI_M = 1024  # 1 GiB on the dense MiB-unit axis


def pods(*dicts):
    return jnp.asarray(
        np.stack([res.resource_vector(d) for d in dicts]).astype(np.int64)
    )


def gpu_node(n_gpus=4, mem_gi=16, free=None):
    devs = []
    for m in range(n_gpus):
        d = {
            "type": "gpu",
            "minor": m,
            "total": {
                res.GPU_CORE: 100,
                res.GPU_MEMORY: f"{mem_gi}Gi",
                res.GPU_MEMORY_RATIO: 100,
            },
        }
        if free and m in free:
            d["free"] = free[m]
        devs.append(d)
    return {"devices": devs}


class TestNormalization:
    def test_ratio_fills_memory(self):
        batch = encode_devices([gpu_node(mem_gi=16)], node_bucket=1)
        dev_req = pod_device_requests(pods({res.GPU_MEMORY_RATIO: 50}))
        norm = np.asarray(
            normalize_gpu_requests(dev_req, gpu_card_total_memory(batch))
        )
        mem = norm[0, 0, 1]  # GPU_MEMORY dim
        assert mem == 8 * GI_M

    def test_memory_fills_ratio(self):
        batch = encode_devices([gpu_node(mem_gi=16)], node_bucket=1)
        dev_req = pod_device_requests(pods({res.GPU_MEMORY: "4Gi"}))
        norm = np.asarray(
            normalize_gpu_requests(dev_req, gpu_card_total_memory(batch))
        )
        assert norm[0, 0, 2] == 25  # ratio dim

    def test_multi_card_split(self):
        dev_req = pod_device_requests(
            pods({res.GPU_CORE: 200, res.GPU_MEMORY_RATIO: 200})
        )
        batch = encode_devices([gpu_node(mem_gi=16)], node_bucket=1)
        norm = normalize_gpu_requests(dev_req, gpu_card_total_memory(batch))
        per_card, wanted = split_per_card(norm)
        assert int(np.asarray(wanted)[0, 0]) == 2
        assert int(np.asarray(per_card)[0, 0, 0]) == 100  # core per card


class TestFit:
    def test_full_cards(self):
        batch = encode_devices(
            [gpu_node(4), gpu_node(1)], node_bucket=2, minor_bucket=4
        )
        p = pods({res.GPU_CORE: 200, res.GPU_MEMORY_RATIO: 200})
        fit = np.asarray(device_fit_mask(p, batch))
        assert fit[0, 0]  # 4 cards satisfy 2 wanted
        assert not fit[0, 1]  # 1 card can't

    def test_partial_share(self):
        # node with one GPU half-used
        half = {
            res.GPU_CORE: 50,
            res.GPU_MEMORY: "8Gi",
            res.GPU_MEMORY_RATIO: 50,
        }
        batch = encode_devices(
            [gpu_node(1, free={0: half})], node_bucket=1, minor_bucket=1
        )
        fits = np.asarray(
            device_fit_mask(pods({res.GPU_MEMORY_RATIO: 50}), batch)
        )
        nofit = np.asarray(
            device_fit_mask(pods({res.GPU_MEMORY_RATIO: 60}), batch)
        )
        assert fits[0, 0]
        assert not nofit[0, 0]

    def test_no_device_request_always_fits(self):
        batch = encode_devices([{"devices": []}], node_bucket=1)
        fit = np.asarray(device_fit_mask(pods({res.CPU: "1"}), batch))
        assert fit[0, 0]

    def test_rdma(self):
        batch = encode_devices(
            [{"devices": [{"type": "rdma", "minor": 0, "total": {res.RDMA: 100}}]},
             {"devices": []}],
            node_bucket=2,
            minor_bucket=1,
        )
        fit = np.asarray(device_fit_mask(pods({res.RDMA: 100}), batch))
        assert fit[0, 0]
        assert not fit[0, 1]  # no rdma device on node-1


class TestScore:
    def test_least_allocated_prefers_empty_node(self):
        half = {
            res.GPU_CORE: 50,
            res.GPU_MEMORY: "8Gi",
            res.GPU_MEMORY_RATIO: 50,
        }
        batch = encode_devices(
            [gpu_node(1), gpu_node(1, free={0: half})],
            node_bucket=2,
            minor_bucket=1,
        )
        p = pods({res.GPU_MEMORY_RATIO: 25})
        scores = np.asarray(deviceshare_scores(p, batch))
        assert scores[0, 0] > scores[0, 1]

    def test_most_allocated_prefers_packed_node(self):
        half = {
            res.GPU_CORE: 50,
            res.GPU_MEMORY: "8Gi",
            res.GPU_MEMORY_RATIO: 50,
        }
        batch = encode_devices(
            [gpu_node(1), gpu_node(1, free={0: half})],
            node_bucket=2,
            minor_bucket=1,
        )
        p = pods({res.GPU_MEMORY_RATIO: 25})
        scores = np.asarray(deviceshare_scores(p, batch, most_allocated=True))
        assert scores[0, 1] > scores[0, 0]


class TestAllocateMinors:
    def _minors(self):
        return [
            {"minor": 0, "total": {"core": 100}, "free": {"core": 100}},
            {"minor": 1, "total": {"core": 100}, "free": {"core": 40}},
            {"minor": 2, "total": {"core": 100}, "free": {"core": 100}},
        ]

    def test_least_allocated_picks_freest_lowest_minor(self):
        got = allocate_minors(self._minors(), {"core": 50}, 1)
        assert got == [0]

    def test_most_allocated_packs(self):
        got = allocate_minors(self._minors(), {"core": 30}, 1, most_allocated=True)
        assert got == [1]

    def test_preferred_first(self):
        got = allocate_minors(self._minors(), {"core": 50}, 1, preferred={2})
        assert got == [2]

    def test_multi_card(self):
        got = allocate_minors(self._minors(), {"core": 100}, 2)
        assert got == [0, 2]

    def test_unsatisfiable_raises(self):
        import pytest

        with pytest.raises(ValueError):
            allocate_minors(self._minors(), {"core": 100}, 3)
