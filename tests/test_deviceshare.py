"""DeviceShare: request normalization, multi-card split, fit, score, minors.

Reference semantics: pkg/scheduler/plugins/deviceshare
(device_cache.go calcDeviceWanted/tryAllocateByDeviceType,
utils.go fillGPUTotalMem, scoring.go scoreNode, device_resources.go sort).
"""

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.model import resources as res
from koordinator_tpu.model.device import encode_devices
from koordinator_tpu.ops.deviceshare import (
    allocate_minors,
    device_fit_mask,
    deviceshare_scores,
    gpu_card_total_memory,
    normalize_gpu_requests,
    pod_device_requests,
    split_per_card,
)

GI = 1024**3
GI_M = 1024  # 1 GiB on the dense MiB-unit axis


def pods(*dicts):
    return jnp.asarray(
        np.stack([res.resource_vector(d) for d in dicts]).astype(np.int64)
    )


def gpu_node(n_gpus=4, mem_gi=16, free=None):
    devs = []
    for m in range(n_gpus):
        d = {
            "type": "gpu",
            "minor": m,
            "total": {
                res.GPU_CORE: 100,
                res.GPU_MEMORY: f"{mem_gi}Gi",
                res.GPU_MEMORY_RATIO: 100,
            },
        }
        if free and m in free:
            d["free"] = free[m]
        devs.append(d)
    return {"devices": devs}


class TestNormalization:
    def test_ratio_fills_memory(self):
        batch = encode_devices([gpu_node(mem_gi=16)], node_bucket=1)
        dev_req = pod_device_requests(pods({res.GPU_MEMORY_RATIO: 50}))
        norm = np.asarray(
            normalize_gpu_requests(dev_req, gpu_card_total_memory(batch))
        )
        mem = norm[0, 0, 1]  # GPU_MEMORY dim
        assert mem == 8 * GI_M

    def test_memory_fills_ratio(self):
        batch = encode_devices([gpu_node(mem_gi=16)], node_bucket=1)
        dev_req = pod_device_requests(pods({res.GPU_MEMORY: "4Gi"}))
        norm = np.asarray(
            normalize_gpu_requests(dev_req, gpu_card_total_memory(batch))
        )
        assert norm[0, 0, 2] == 25  # ratio dim

    def test_multi_card_split(self):
        dev_req = pod_device_requests(
            pods({res.GPU_CORE: 200, res.GPU_MEMORY_RATIO: 200})
        )
        batch = encode_devices([gpu_node(mem_gi=16)], node_bucket=1)
        norm = normalize_gpu_requests(dev_req, gpu_card_total_memory(batch))
        per_card, wanted = split_per_card(norm)
        assert int(np.asarray(wanted)[0, 0]) == 2
        assert int(np.asarray(per_card)[0, 0, 0]) == 100  # core per card


class TestFit:
    def test_full_cards(self):
        batch = encode_devices(
            [gpu_node(4), gpu_node(1)], node_bucket=2, minor_bucket=4
        )
        p = pods({res.GPU_CORE: 200, res.GPU_MEMORY_RATIO: 200})
        fit = np.asarray(device_fit_mask(p, batch))
        assert fit[0, 0]  # 4 cards satisfy 2 wanted
        assert not fit[0, 1]  # 1 card can't

    def test_partial_share(self):
        # node with one GPU half-used
        half = {
            res.GPU_CORE: 50,
            res.GPU_MEMORY: "8Gi",
            res.GPU_MEMORY_RATIO: 50,
        }
        batch = encode_devices(
            [gpu_node(1, free={0: half})], node_bucket=1, minor_bucket=1
        )
        fits = np.asarray(
            device_fit_mask(pods({res.GPU_MEMORY_RATIO: 50}), batch)
        )
        nofit = np.asarray(
            device_fit_mask(pods({res.GPU_MEMORY_RATIO: 60}), batch)
        )
        assert fits[0, 0]
        assert not nofit[0, 0]

    def test_no_device_request_always_fits(self):
        batch = encode_devices([{"devices": []}], node_bucket=1)
        fit = np.asarray(device_fit_mask(pods({res.CPU: "1"}), batch))
        assert fit[0, 0]

    def test_rdma(self):
        batch = encode_devices(
            [{"devices": [{"type": "rdma", "minor": 0, "total": {res.RDMA: 100}}]},
             {"devices": []}],
            node_bucket=2,
            minor_bucket=1,
        )
        fit = np.asarray(device_fit_mask(pods({res.RDMA: 100}), batch))
        assert fit[0, 0]
        assert not fit[0, 1]  # no rdma device on node-1


class TestScore:
    def test_least_allocated_prefers_empty_node(self):
        half = {
            res.GPU_CORE: 50,
            res.GPU_MEMORY: "8Gi",
            res.GPU_MEMORY_RATIO: 50,
        }
        batch = encode_devices(
            [gpu_node(1), gpu_node(1, free={0: half})],
            node_bucket=2,
            minor_bucket=1,
        )
        p = pods({res.GPU_MEMORY_RATIO: 25})
        scores = np.asarray(deviceshare_scores(p, batch))
        assert scores[0, 0] > scores[0, 1]

    def test_most_allocated_prefers_packed_node(self):
        half = {
            res.GPU_CORE: 50,
            res.GPU_MEMORY: "8Gi",
            res.GPU_MEMORY_RATIO: 50,
        }
        batch = encode_devices(
            [gpu_node(1), gpu_node(1, free={0: half})],
            node_bucket=2,
            minor_bucket=1,
        )
        p = pods({res.GPU_MEMORY_RATIO: 25})
        scores = np.asarray(deviceshare_scores(p, batch, most_allocated=True))
        assert scores[0, 1] > scores[0, 0]


class TestAllocateMinors:
    def _minors(self):
        return [
            {"minor": 0, "total": {"core": 100}, "free": {"core": 100}},
            {"minor": 1, "total": {"core": 100}, "free": {"core": 40}},
            {"minor": 2, "total": {"core": 100}, "free": {"core": 100}},
        ]

    def test_least_allocated_picks_freest_lowest_minor(self):
        got = allocate_minors(self._minors(), {"core": 50}, 1)
        assert got == [0]

    def test_most_allocated_packs(self):
        got = allocate_minors(self._minors(), {"core": 30}, 1, most_allocated=True)
        assert got == [1]

    def test_preferred_first(self):
        got = allocate_minors(self._minors(), {"core": 50}, 1, preferred={2})
        assert got == [2]

    def test_multi_card(self):
        got = allocate_minors(self._minors(), {"core": 100}, 2)
        assert got == [0, 2]

    def test_unsatisfiable_raises(self):
        import pytest

        with pytest.raises(ValueError):
            allocate_minors(self._minors(), {"core": 100}, 3)


class TestPartitionTables:
    """GPU partition tables (newer koordinator apis/extension semantics):
    multi-card sets must be one listed group, e.g. NVLink rings."""

    def _minors(self, used=()):
        out = []
        for i in range(8):
            free = 20 if i in used else 100
            out.append(
                {
                    "minor": i,
                    "total": {"koordinator.sh/gpu-core": 100},
                    "free": {"koordinator.sh/gpu-core": free},
                    "topology": {"numaNode": i // 4},
                }
            )
        return out

    PART = {4: [[0, 1, 2, 3], [4, 5, 6, 7]], 8: [list(range(8))]}

    def test_partition_group_chosen_whole(self):
        from koordinator_tpu.ops.deviceshare import allocate_partitioned

        # minor 1 is busy: group [0,1,2,3] infeasible -> the OTHER ring
        # must be taken whole, even though free minors 0,2,3,4 would win
        # a per-minor greedy
        got = allocate_partitioned(
            self._minors(used=(1,)),
            {"koordinator.sh/gpu-core": 100},
            4,
            self.PART,
        )
        assert got == [4, 5, 6, 7]

    def test_no_feasible_group_raises(self):
        import pytest
        from koordinator_tpu.ops.deviceshare import allocate_partitioned

        with pytest.raises(ValueError):
            allocate_partitioned(
                self._minors(used=(1, 5)),
                {"koordinator.sh/gpu-core": 100},
                4,
                self.PART,
            )

    def test_size_without_table_falls_back(self):
        from koordinator_tpu.ops.deviceshare import allocate_partitioned

        got = allocate_partitioned(
            self._minors(used=(0,)),
            {"koordinator.sh/gpu-core": 100},
            2,
            self.PART,
        )
        assert got == [1, 2]  # plain least-allocated ordering

    def test_partition_fit_mask_refines_tensor_fit(self):
        import numpy as np

        from koordinator_tpu.model.device import encode_devices
        from koordinator_tpu.ops.deviceshare import (
            device_fit_mask,
            partition_fit_mask,
        )

        # node 0: minors 1 and 5 busy -> 6 free minors, but NO 4-ring free
        devs = []
        for i in range(8):
            free = 20 if i in (1, 5) else 100
            devs.append(
                {
                    "type": "gpu",
                    "minor": i,
                    "total": {"koordinator.sh/gpu-core": 100,
                              "koordinator.sh/gpu-memory": 16 << 30,
                              "koordinator.sh/gpu-memory-ratio": 100},
                    "free": {"koordinator.sh/gpu-core": free,
                             "koordinator.sh/gpu-memory": 16 << 30,
                             "koordinator.sh/gpu-memory-ratio": free},
                }
            )
        batch = encode_devices([{"devices": devs}], node_bucket=1)
        reqs = pods({"koordinator.sh/gpu-core": 400,
                     "koordinator.sh/gpu-memory-ratio": 400})
        tensor_fit = np.asarray(device_fit_mask(reqs, batch))
        assert tensor_fit[0, 0]  # count-based fit overcounts
        refined = partition_fit_mask(reqs, batch, {0: self.PART})
        assert not refined[0, 0]  # no single ring is free

    def test_partition_fit_mask_minor_id_space_multi_type(self):
        """Filter and Reserve must read device_partitions in the SAME id
        space (CR minor ids).  On a multi-type node an RDMA minor 0 sits
        at slot 0 while GPU minors 0..3 occupy slots 1..4 — indexing the
        groups as dense slots would test the wrong cells (the advisory's
        Filter/Reserve divergence)."""
        import numpy as np

        from koordinator_tpu.model.device import encode_devices
        from koordinator_tpu.ops.deviceshare import (
            allocate_partitioned,
            minor_dicts_from_batch,
            partition_fit_mask,
        )

        devs = [
            {
                "type": "rdma",
                "minor": 0,
                "total": {"koordinator.sh/rdma": 100},
                "free": {"koordinator.sh/rdma": 100},
            }
        ]
        for i in range(4):
            devs.append(
                {
                    "type": "gpu",
                    "minor": i,
                    "total": {"koordinator.sh/gpu-core": 100,
                              "koordinator.sh/gpu-memory": 16 << 30,
                              "koordinator.sh/gpu-memory-ratio": 100},
                    "free": {"koordinator.sh/gpu-core": 100,
                             "koordinator.sh/gpu-memory": 16 << 30,
                             "koordinator.sh/gpu-memory-ratio": 100},
                }
            )
        batch = encode_devices([{"devices": devs}], node_bucket=1)
        part = {2: [[0, 1], [2, 3]]}
        reqs = pods({"koordinator.sh/gpu-core": 200,
                     "koordinator.sh/gpu-memory-ratio": 200})
        refined = partition_fit_mask(reqs, batch, {0: part})
        assert refined[0, 0]  # all GPU minors free: group [0,1] fits

        # Reserve's view agrees: the same table allocates without raising
        minors = [
            m for m in minor_dicts_from_batch(batch, 0) if m["type"] == "gpu"
        ]
        got = allocate_partitioned(
            minors,
            {"koordinator.sh/gpu-core": 100,
             "koordinator.sh/gpu-memory-ratio": 100},
            2,
            part,
        )
        assert got == [0, 1]


class TestJointAllocation:
    """allocate_joint: all requested types on one node, NUMA-aligned
    (reference device_cache.go:272 tryAllocateDevice; allocator.go:91)."""

    def _minors(self):
        out = []
        for i in range(4):  # GPUs: 0,1 on numa0; 2,3 on numa1
            out.append(
                {
                    "type": "gpu",
                    "minor": i,
                    "total": {"koordinator.sh/gpu-core": 100},
                    "free": {"koordinator.sh/gpu-core": 100 if i >= 2 else 30},
                    "topology": {"numaNode": i // 2},
                }
            )
        for i in range(2):  # one RDMA NIC per numa node
            out.append(
                {
                    "type": "rdma",
                    "minor": 10 + i,
                    "total": {"koordinator.sh/rdma": 100},
                    "free": {"koordinator.sh/rdma": 100},
                    "topology": {"numaNode": i},
                }
            )
        return out

    def test_rdma_follows_gpu_numa(self):
        from koordinator_tpu.model.device import DEVICE_GPU, DEVICE_RDMA
        from koordinator_tpu.ops.deviceshare import allocate_joint

        got = allocate_joint(
            self._minors(),
            {
                DEVICE_GPU: {"koordinator.sh/gpu-core": 100},
                DEVICE_RDMA: {"koordinator.sh/rdma": 50},
            },
            {DEVICE_GPU: 1, DEVICE_RDMA: 1},
        )
        # only numa1 GPUs have 100 free; the RDMA tiebreak (both NICs
        # equally free) must follow the GPU onto numa1
        assert got[DEVICE_GPU] == [2]
        assert got[DEVICE_RDMA] == [11]

    def test_all_or_nothing(self):
        import pytest

        from koordinator_tpu.model.device import DEVICE_FPGA, DEVICE_GPU
        from koordinator_tpu.ops.deviceshare import allocate_joint

        with pytest.raises(ValueError):
            allocate_joint(
                self._minors(),
                {
                    DEVICE_GPU: {"koordinator.sh/gpu-core": 50},
                    DEVICE_FPGA: {"koordinator.sh/fpga": 100},
                },
                {DEVICE_GPU: 1, DEVICE_FPGA: 1},
            )

    def test_gpu_partition_table_applies_in_joint(self):
        from koordinator_tpu.model.device import DEVICE_GPU
        from koordinator_tpu.ops.deviceshare import allocate_joint

        minors = [
            {
                "type": "gpu",
                "minor": i,
                "total": {"koordinator.sh/gpu-core": 100},
                "free": {"koordinator.sh/gpu-core": 100 if i != 0 else 10},
                "topology": {"numaNode": i // 2},
            }
            for i in range(4)
        ]
        got = allocate_joint(
            minors,
            {DEVICE_GPU: {"koordinator.sh/gpu-core": 100}},
            {DEVICE_GPU: 2},
            partitions={2: [[0, 1], [2, 3]]},
        )
        # minor 0 busy -> pair [0,1] infeasible; [2,3] taken whole
        assert got[DEVICE_GPU] == [2, 3]


class TestMixedTypeRequests:
    """A multi-card GPU pod co-requesting RDMA must NOT have its RDMA
    quantity divided by the GPU card count (round-4 review regression)."""

    def _node(self):
        devs = [
            {
                "type": "gpu",
                "minor": i,
                "total": {"koordinator.sh/gpu-core": 100,
                          "koordinator.sh/gpu-memory": 16 << 30,
                          "koordinator.sh/gpu-memory-ratio": 100},
            }
            for i in range(4)
        ] + [
            {"type": "rdma", "minor": 0, "total": {"koordinator.sh/rdma": 100}}
        ]
        return {"devices": devs}

    def test_fit_requires_full_rdma_on_one_nic(self):
        import numpy as np

        from koordinator_tpu.model.device import encode_devices
        from koordinator_tpu.ops.deviceshare import (
            device_fit_mask,
            pod_device_requests,
            split_per_card,
            normalize_gpu_requests,
            gpu_card_total_memory,
        )

        batch = encode_devices([self._node()], node_bucket=1)
        reqs = pods({"koordinator.sh/gpu-core": 400,
                     "koordinator.sh/gpu-memory-ratio": 400,
                     "koordinator.sh/rdma": 100})
        # per-card split: GPU dims divided by 4, rdma kept whole
        norm = normalize_gpu_requests(
            pod_device_requests(reqs), gpu_card_total_memory(batch)
        )
        per_card, wanted = split_per_card(norm)
        from koordinator_tpu.model.device import DEVICE_RESOURCE_INDEX

        pc = np.asarray(per_card)[0, 0]
        assert pc[DEVICE_RESOURCE_INDEX["koordinator.sh/gpu-core"]] == 100
        assert pc[DEVICE_RESOURCE_INDEX["koordinator.sh/rdma"]] == 100
        assert int(np.asarray(wanted)[0, 0]) == 4
        assert bool(np.asarray(device_fit_mask(reqs, batch))[0, 0])

    def test_joint_reserve_deducts_full_rdma(self):
        import jax.numpy as jnp
        import numpy as np

        from koordinator_tpu.model import encode_snapshot
        from koordinator_tpu.model.device import encode_devices
        from koordinator_tpu.scheduler.framework import CycleContext
        from koordinator_tpu.scheduler.plugins import DeviceSharePlugin

        batch = encode_devices([self._node()], node_bucket=8)
        snap = encode_snapshot(
            [{"name": "n0", "allocatable": {"cpu": "32", "memory": "64Gi"}}],
            [{"name": "p0", "requests": {
                "cpu": "1",
                "koordinator.sh/gpu-core": 400,
                "koordinator.sh/gpu-memory-ratio": 400,
                "koordinator.sh/rdma": 100,
            }}],
            [],
            [],
        )
        plugin = DeviceSharePlugin()
        ctx = CycleContext(snapshot=snap, extras={"devices": batch})
        plugin.reserve(ctx, 0, 0)
        alloc = ctx.state["device_allocations"][0]

        # the reference DeviceAllocations shape (device_share.go:56-66)
        assert [e["minor"] for e in alloc["gpu"]] == [0, 1, 2, 3]
        # the NIC reports its CR minor (per-type numbering), not its slot
        assert [e["minor"] for e in alloc["rdma"]] == [0]
        # the NIC's free rdma went to 0: full quantity deducted
        minors = ctx.extras["device_minors"][0]
        nic = next(m for m in minors if m["type"] == "rdma")
        from koordinator_tpu.model import resources as res

        assert res.parse_quantity(nic["free"]["koordinator.sh/rdma"],
                                  "koordinator.sh/rdma") == 0
