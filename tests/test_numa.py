"""NodeNUMAResource: cpuset accumulator, topology hints, zone kernels.

The accumulator cases replicate the reference's table tests
(reference pkg/scheduler/plugins/nodenumaresource/cpu_accumulator_test.go:59
TestTakeFullPCPUs and the NUMALeastAllocated variant at :180) input-for-input
so placement parity is checked against the exact expected cpusets.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from koordinator_tpu.model import resources as res
from koordinator_tpu.model.topology import CPUTopology, amplify, encode_zones
from koordinator_tpu.ops.numa import (
    POLICY_BEST_EFFORT,
    POLICY_RESTRICTED,
    POLICY_SINGLE_NUMA_NODE,
    amplified_cpu_scores,
    numa_admit_mask,
    numa_zone_scores,
    zone_fit_mask,
)
from koordinator_tpu.scheduler import (
    CPUBindPolicy,
    NUMAAllocateStrategy,
    NUMATopologyHint,
    NUMATopologyPolicy,
    merge_hints,
    take_cpus,
    take_preferred_cpus,
)
from koordinator_tpu.scheduler.cpu_accumulator import (
    CPUAllocation,
    CPUAllocationError,
)
from koordinator_tpu.scheduler.topologymanager import generate_cpu_hints


def parse_set(s):
    """cpuset.MustParse-style '0-5,16-23'."""
    out = set()
    for part in s.split(","):
        if "-" in part:
            a, b = part.split("-")
            out |= set(range(int(a), int(b) + 1))
        elif part:
            out.add(int(part))
    return out


def _take(topo_args, allocated, needed, strategy, policy=CPUBindPolicy.FULL_PCPUS):
    topo = CPUTopology.build(*topo_args)
    available = set(topo.details) - allocated
    got = take_cpus(
        topo, available, needed, bind_policy=policy, strategy=strategy
    )
    assert len(got) == needed
    return set(got)


# (topology args, allocated, needed, expected) —
# cpu_accumulator_test.go TestTakeFullPCPUs (NUMAMostAllocated)
MOST_ALLOCATED_CASES = [
    ((1, 1, 4, 2), set(), 2, {0, 1}),
    ((1, 1, 4, 2), {0, 1}, 2, {2, 3}),
    ((2, 1, 4, 2), set(), 8, parse_set("0-7")),
    ((2, 1, 4, 2), set(), 12, parse_set("0-11")),
    ((2, 1, 4, 2), {0, 1}, 8, parse_set("8-15")),
    ((2, 2, 4, 2), parse_set("0-5,16-23"), 6, parse_set("24-29")),
    ((2, 2, 4, 2), parse_set("0-5,16-23"), 12, parse_set("6-15,24-25")),
    ((2, 2, 4, 2), parse_set("0-3,8-11"), 4, parse_set("4-7")),
    ((2, 2, 2, 2), {0, 2, 4, 8, 12}, 4, {10, 11, 14, 15}),
    ((2, 2, 2, 2), {0, 2, 4, 8, 10, 12}, 6, {5, 6, 7, 13, 14, 15}),
    ((2, 2, 2, 2), {0, 2, 4, 8, 9, 10, 12}, 6, {6, 7, 11, 13, 14, 15}),
]

# cpu_accumulator_test.go:180 variant (NUMALeastAllocated)
LEAST_ALLOCATED_CASES = [
    ((1, 1, 4, 2), set(), 2, {0, 1}),
    ((1, 1, 4, 2), {0, 1}, 2, {2, 3}),
    ((2, 1, 4, 2), set(), 8, parse_set("0-7")),
    ((2, 1, 4, 2), set(), 12, parse_set("0-11")),
    ((2, 1, 4, 2), {0, 1}, 8, parse_set("8-15")),
    ((2, 2, 4, 2), parse_set("0-5,16-23"), 6, parse_set("8-13")),
    ((2, 2, 4, 2), parse_set("0-5,16-23"), 12, parse_set("6-15,24-25")),
    ((2, 2, 4, 2), parse_set("0-3,8-11"), 4, parse_set("16-19")),
    ((2, 2, 2, 2), {0, 2, 4, 8, 12}, 4, {10, 11, 14, 15}),
    ((2, 2, 2, 2), {0, 2, 4, 8, 10, 12}, 6, {6, 7, 14, 15, 1, 3}),
    ((2, 2, 4, 2), {0, 2, 4, 8, 9, 10, 12}, 6, parse_set("16-21")),
]


class TestCPUAccumulator:
    @pytest.mark.parametrize("topo_args,allocated,needed,want", MOST_ALLOCATED_CASES)
    def test_full_pcpus_most_allocated(self, topo_args, allocated, needed, want):
        got = _take(topo_args, allocated, needed, NUMAAllocateStrategy.MOST_ALLOCATED)
        assert got == want

    @pytest.mark.parametrize("topo_args,allocated,needed,want", LEAST_ALLOCATED_CASES)
    def test_full_pcpus_least_allocated(self, topo_args, allocated, needed, want):
        got = _take(topo_args, allocated, needed, NUMAAllocateStrategy.LEAST_ALLOCATED)
        assert got == want

    def test_spread_by_pcpus_one_per_core(self):
        topo = CPUTopology.build(1, 1, 4, 2)
        got = take_cpus(
            topo,
            set(topo.details),
            4,
            bind_policy=CPUBindPolicy.SPREAD_BY_PCPUS,
            strategy=NUMAAllocateStrategy.MOST_ALLOCATED,
        )
        # one cpu from each of the 4 cores
        assert {topo.details[c].core for c in got} == {0, 1, 2, 3}

    def test_not_enough_cpus(self):
        topo = CPUTopology.build(1, 1, 2, 2)
        with pytest.raises(CPUAllocationError):
            take_cpus(topo, {0, 1}, 3)

    def test_preferred_cpus_taken_first(self):
        topo = CPUTopology.build(2, 1, 4, 2)
        got = take_preferred_cpus(
            topo, set(topo.details), preferred={8, 9}, num_needed=4
        )
        assert {8, 9} <= set(got)
        assert len(got) == 4

    def test_exclusive_pcpu_level_avoids_marked_cores(self):
        # cpu_accumulator_test.go:457 "allocate overlapped cpus with PCPULevel":
        # with core 0 marked exclusive, a new PCPULevel pod lands elsewhere.
        topo = CPUTopology.build(2, 1, 4, 2)
        allocated = CPUAllocation(
            ref_count={0: 1, 1: 1},
            exclusive_policy={0: "PCPULevel", 1: "PCPULevel"},
        )
        from koordinator_tpu.scheduler import CPUExclusivePolicy

        got = take_cpus(
            topo,
            set(topo.details) - {0, 1},
            2,
            allocated=allocated,
            exclusive_policy=CPUExclusivePolicy.PCPU_LEVEL,
        )
        assert {topo.details[c].core for c in got} & {0} == set()


class TestTopologyManager:
    def test_policy_none_always_admits(self):
        hint, admit = merge_hints(NUMATopologyPolicy.NONE, [0, 1], [])
        assert admit and hint.affinity is None

    def test_single_numa_node_prefers_one_node(self):
        hints = [{"cpu": [NUMATopologyHint(0b01, True), NUMATopologyHint(0b11, False)]}]
        hint, admit = merge_hints(NUMATopologyPolicy.SINGLE_NUMA_NODE, [0, 1], hints)
        assert admit and hint.affinity == 0b01 and hint.preferred

    def test_single_numa_node_rejects_cross_node_only(self):
        hints = [{"cpu": [NUMATopologyHint(0b11, False)]}]
        hint, admit = merge_hints(NUMATopologyPolicy.SINGLE_NUMA_NODE, [0, 1], hints)
        assert not admit

    def test_restricted_rejects_unpreferred(self):
        hints = [{"cpu": [NUMATopologyHint(0b11, False)]}]
        _, admit = merge_hints(NUMATopologyPolicy.RESTRICTED, [0, 1], hints)
        assert not admit

    def test_best_effort_admits_unpreferred(self):
        hints = [{"cpu": [NUMATopologyHint(0b11, False)]}]
        hint, admit = merge_hints(NUMATopologyPolicy.BEST_EFFORT, [0, 1], hints)
        assert admit and hint.affinity == 0b11

    def test_cross_provider_intersection(self):
        # cpu prefers node0, device prefers node0|node1 -> merged node0
        hints = [
            {"cpu": [NUMATopologyHint(0b01, True)]},
            {"device": [NUMATopologyHint(0b01, True), NUMATopologyHint(0b10, True)]},
        ]
        hint, admit = merge_hints(NUMATopologyPolicy.BEST_EFFORT, [0, 1], hints)
        assert admit and hint.affinity == 0b01 and hint.preferred

    def test_generate_cpu_hints_minimal_width_preferred(self):
        hints = generate_cpu_hints({0: 4, 1: 8}, 6)["cpu"]
        by_mask = {h.affinity: h for h in hints}
        assert by_mask[0b10].preferred  # node1 alone fits
        assert not by_mask[0b11].preferred  # pair is wider
        assert 0b01 not in by_mask  # node0 alone can't fit


def _zones(node_specs):
    return encode_zones(node_specs, node_bucket=len(node_specs))


class TestZoneKernels:
    def setup_method(self):
        self.zb = _zones(
            [
                {
                    "zones": [
                        {"allocatable": {"cpu": "8", "memory": "16Gi"}},
                        {
                            "allocatable": {"cpu": "8", "memory": "16Gi"},
                            "requested": {"cpu": "6", "memory": "12Gi"},
                        },
                    ]
                },
                {"zones": []},  # node without NRT
            ]
        )
        self.pods = jnp.asarray(
            np.array(
                [
                    res.resource_vector({"cpu": "4", "memory": "8Gi"}),
                    res.resource_vector({"cpu": "12", "memory": "1Gi"}),
                ],
                dtype=np.int64,
            )
        )

    def test_zone_fit(self):
        fits = np.asarray(
            zone_fit_mask(
                self.pods, self.zb.allocatable, self.zb.requested, self.zb.valid
            )
        )
        # pod0 (4c) fits zone0 (free 8c) but not zone1 (free 2c)
        assert fits[0, 0, 0] and not fits[0, 0, 1]
        # pod1 (12c) fits no single zone
        assert not fits[1, 0].any()

    def test_admit_by_policy(self):
        for policy, want_pod1 in [
            (POLICY_SINGLE_NUMA_NODE, False),
            (POLICY_RESTRICTED, False),  # union free cpu = 10 < 12
            (POLICY_BEST_EFFORT, True),
        ]:
            admit = np.asarray(
                numa_admit_mask(
                    self.pods,
                    self.zb.allocatable,
                    self.zb.requested,
                    self.zb.valid,
                    jnp.full((2,), policy, jnp.int32),
                )
            )
            assert admit[0, 0], policy
            assert admit[1, 0] == want_pod1, policy
            # node without zones always admits
            assert admit[:, 1].all(), policy

    def test_zone_scores_pick_allocator_zone(self):
        weights = jnp.asarray(
            np.array([1 if r in ("cpu", "memory") else 0 for r in res.RESOURCE_AXIS]),
            dtype=jnp.int64,
        )
        scores = np.asarray(
            numa_zone_scores(
                self.pods,
                self.zb.allocatable,
                self.zb.requested,
                self.zb.valid,
                weights,
                most_allocated=False,
            )
        )
        # pod0 on node0: only zone0 fits -> least-allocated score of zone0
        # after placement: cpu (8-4)/8*100=50, mem (16-8)/16*100=50 -> 50
        assert scores[0, 0] == 50
        # pod1 fits nowhere on node0 -> 0
        assert scores[1, 0] == 0

    def test_amplify_fixed_point(self):
        assert amplify(1000, 10_000) == 1000  # ratio 1.0
        assert amplify(1000, 15_000) == 1500
        assert amplify(1, 15_000) == 2  # ceil
        assert amplify(1000, 5_000) == 1000  # ratios < 1 don't shrink

    def test_amplified_cpu_scores_parity(self):
        # one node: allocatable 32c (amplified), 8000m held by cpuset pods,
        # ratio 2.0 -> requested' = req - 8000 + 16000
        R = res.NUM_RESOURCES
        cpu = res.RESOURCE_INDEX[res.CPU]
        node_alloc = np.zeros((1, R), np.int64)
        node_alloc[0, cpu] = 32_000
        node_req = np.zeros((1, R), np.int64)
        node_req[0, cpu] = 10_000
        pod = np.zeros((1, R), np.int64)
        pod[0, cpu] = 2_000
        weights = np.zeros((R,), np.int64)
        weights[cpu] = 1
        scores = np.asarray(
            amplified_cpu_scores(
                jnp.asarray(pod),
                jnp.asarray(node_req),
                jnp.asarray(node_alloc),
                jnp.asarray(np.array([8_000], np.int64)),
                jnp.asarray(np.array([20_000], np.int32)),
                jnp.asarray(weights),
            )
        )
        # requested' = 10000-8000+16000 = 18000; +pod 2000 = 20000
        # least: (32000-20000)*100/32000 = 37 (int div)
        assert scores[0, 0] == 37


class TestTopologyModel:
    def test_build_counts(self):
        topo = CPUTopology.build(2, 2, 4, 2)
        assert topo.num_cpus == 32
        assert topo.num_cores == 16
        assert topo.num_nodes == 4
        assert topo.num_sockets == 2
        assert topo.cpus_per_core() == 2
        assert topo.cpus_per_node() == 8
        assert topo.cpus_per_socket() == 16
        assert topo.cpus_in_node(0) == list(range(8))
        assert topo.cpus_in_core(0) == [0, 1]
