"""bench.py budget accounting — stdlib only (no jax import).

BENCH_r05 was rc=124 with an EMPTY tail: the driver's axe landed before
the CPU-fallback artifact printed, because stage windows could overshoot
the total budget (an unclamped inter-probe sleep, and a 60s floor on the
CPU window applied even when less than 60s remained).  The invariant
locked in here: replaying ``parent()``'s exact window-request sequence
against ``_Budget`` — worst case, every stage consuming its full grant
and every probe retrying — the granted seconds sum to <= the budget for
ANY ``KOORD_BENCH_TOTAL_BUDGET``, and the CPU-fallback artifact stage
always receives a positive window whenever any budget remains.
"""

import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "bench_under_test", os.path.join(REPO, "bench.py")
)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)

PROBE_TIMEOUT = 120.0
TPU_TIMEOUT = 600.0
CPU_TIMEOUT = 900.0


def _drain(total, probe_wait=2400.0):
    """Replay parent()'s window requests against _Budget with a fake
    clock that burns every granted second (the worst case the driver's
    timeout must survive).  Returns (granted windows, cpu window)."""
    now = [0.0]
    b = bench._Budget(
        total, reserve=CPU_TIMEOUT + 60.0, clock=lambda: now[0]
    )
    granted = []

    def spend(sec):
        granted.append(sec)
        now[0] += sec

    # _probe_until: repeated probe children + clamped inter-probe sleeps
    deadline = now[0] + b.window(probe_wait)
    while True:
        left = deadline - now[0]
        if left <= 0 or b.window(PROBE_TIMEOUT) <= 0:
            break
        spend(max(1.0, min(PROBE_TIMEOUT, left)))
        if now[0] >= deadline:
            break
        spend(min(30.0, deadline - now[0]))

    # up to three TPU attempts with a reprobe between retries
    for attempt, timeout in enumerate(
        (TPU_TIMEOUT, TPU_TIMEOUT, TPU_TIMEOUT * 3 // 4)
    ):
        w = b.window(timeout)
        if w <= 60:
            break
        spend(w)
        if attempt < 2:
            rw = b.window(PROBE_TIMEOUT)
            if rw <= 0:
                break
            spend(rw)

    # the CPU-fallback artifact stage (reserve released)
    cpu = b.window(CPU_TIMEOUT, reserve=0.0)
    if cpu > 0:
        spend(cpu)
    return granted, cpu


class TestBudgetInvariant:
    def test_windows_sum_to_at_most_the_budget(self):
        for total in (5.0, 30.0, 120.0, 600.0, 1200.0, 2400.0, 3600.0,
                      10000.0):
            granted, _ = _drain(total)
            assert sum(granted) <= total + 1e-6, (
                f"budget {total}: granted {sum(granted)} "
                f"across {len(granted)} windows"
            )

    def test_cpu_fallback_always_gets_a_window(self):
        # whatever the probe/TPU stages consumed, the artifact stage is
        # never starved: with any budget at all, the CPU child runs
        for total in (5.0, 30.0, 120.0, 2400.0, 10000.0):
            _, cpu = _drain(total)
            assert cpu > 0, f"budget {total}: cpu fallback starved"

    def test_full_cpu_slot_survives_the_probe_window(self):
        # in a normal-sized budget the reserve holds back a FULL CPU
        # slot even when probing and TPU attempts eat their maximum
        _, cpu = _drain(2400.0)
        assert cpu >= min(CPU_TIMEOUT, 60.0)

    def test_window_never_exceeds_remaining(self):
        now = [0.0]
        b = bench._Budget(100.0, reserve=30.0, clock=lambda: now[0])
        assert b.window(1000.0) <= 70.0
        now[0] = 90.0
        assert b.window(1000.0) <= 10.0
        assert b.window(1000.0, reserve=0.0) <= 10.0
        now[0] = 200.0
        assert b.window(1000.0, reserve=0.0) == 0.0


class TestArtifactDeadline:
    """ISSUE 5 satellite: the rc=124-no-artifact class, closed for
    real.  The budget accountant bounds the windows bench GRANTS
    itself, but a stage that hangs past its window — or a driver
    timeout shorter than the budget — used to kill the process with
    nothing on stdout (BENCH_r05).  The hard deadline replays here
    under an injected clock: a slow stage never returns, the watchdog
    fires, and a schema-valid artifact with ``"truncated": true`` is
    flushed before exit."""

    def _deadline(self, total=100.0, start=0.0):
        emitted = []
        fired = []
        now = [start]

        def sleep(s):
            # the injected clock IS the slow stage: every watchdog nap
            # burns fake seconds while the "stage" never completes
            now[0] += s

        d = bench._ArtifactDeadline(
            total,
            emit=lambda line: emitted.append(line) or True,
            clock=lambda: now[0],
            sleep=sleep,
            on_fire=lambda rc: fired.append(rc),
        )
        return d, emitted, fired, now

    def test_slow_stage_flushes_truncated_artifact(self):
        d, emitted, fired, _now = self._deadline(total=100.0)
        bench._PROGRESS["stage"] = "tpu_attempt_2"
        d.watch()  # fake clock: returns once the deadline elapsed
        assert fired == [1]
        assert len(emitted) == 1
        assert bench._validate_artifact(emitted[0]) == []
        doc = json.loads(emitted[0])
        assert doc["truncated"] is True
        assert "tpu_attempt_2" in doc["error"]

    def test_fire_is_idempotent(self):
        d, emitted, fired, _now = self._deadline()
        d.fire("SIGTERM from the driver")
        d.fire("hard wall-clock deadline reached before an artifact")
        assert fired == [1] and len(emitted) == 1
        assert "SIGTERM" in json.loads(emitted[0])["error"]

    def test_cancel_after_real_artifact_suppresses_the_flush(self):
        d, emitted, fired, _now = self._deadline()
        d.cancel()  # a real artifact line made it out
        d.fire("hard wall-clock deadline reached before an artifact")
        assert emitted == [] and fired == []

    def test_deadline_respects_margin(self):
        d, _e, _f, _now = self._deadline(total=100.0)
        assert d.deadline == pytest.approx(70.0)  # 30s margin
        # tiny budgets never go non-positive
        d2, _e, _f, _now = self._deadline(total=5.0)
        assert d2.deadline >= 1.0

    def test_truncated_artifact_line_is_schema_valid(self):
        d, _e, _f, _now = self._deadline()
        line = d.artifact_line("reason")
        assert bench._validate_artifact(line) == []


class TestArtifactSchemaTruncatedAndCoalesce:
    def _line(self, **extra):
        doc = {"metric": "m", "value": 1.0, "unit": "ms"}
        doc.update(extra)
        return json.dumps(doc)

    def test_truncated_must_be_bool(self):
        assert bench._validate_artifact(self._line(truncated=True)) == []
        assert bench._validate_artifact(self._line(truncated=False)) == []
        assert bench._validate_artifact(self._line(truncated="yes"))
        assert bench._validate_artifact(self._line(truncated=1))

    def test_concurrency_probe_fields(self):
        assert bench._validate_artifact(self._line(
            concurrency=8, coalesce_batch_mean=5.3,
            p50_score_ms=12.0, p99_score_ms=40.5,
            score_concurrent_speedup=4.2,
        )) == []
        assert bench._validate_artifact(self._line(concurrency=0))
        assert bench._validate_artifact(self._line(concurrency=True))
        assert bench._validate_artifact(self._line(coalesce_batch_mean=0.5))
        assert bench._validate_artifact(self._line(p99_score_ms=-1))
        assert bench._validate_artifact(
            self._line(score_concurrent_speedup=float("nan"))
        )

    def test_pipeline_probe_fields(self):
        # ISSUE 6: the pipelined-dispatch health fields must be archived
        # well-formed or not at all
        assert bench._validate_artifact(self._line(
            score_pipeline_speedup=2.1, device_idle_ms=0.4,
            coalesce_window_ms=1.5, launch_overlaps=37,
        )) == []
        assert bench._validate_artifact(self._line(
            device_idle_ms=None, coalesce_window_ms=None,
            score_pipeline_speedup=None, launch_overlaps=None,
        )) == []
        assert bench._validate_artifact(self._line(device_idle_ms=-1))
        assert bench._validate_artifact(
            self._line(coalesce_window_ms=float("inf"))
        )
        assert bench._validate_artifact(
            self._line(score_pipeline_speedup=float("nan"))
        )
        assert bench._validate_artifact(self._line(launch_overlaps=-3))
        assert bench._validate_artifact(self._line(launch_overlaps=True))
        assert bench._validate_artifact(self._line(launch_overlaps=1.5))

    def test_serial_sample_field(self):
        assert bench._validate_artifact(
            self._line(score_serial_sample=8)
        ) == []
        assert bench._validate_artifact(
            self._line(score_serial_sample=None)
        ) == []
        assert bench._validate_artifact(self._line(score_serial_sample=0))
        assert bench._validate_artifact(self._line(score_serial_sample=True))
        assert bench._validate_artifact(self._line(score_serial_sample=2.5))

    def test_serial_extrapolation(self):
        # the serialized baseline is one-request-at-a-time, so a sampled
        # storm wall scales linearly to the full request count — and a
        # full (or degenerate) sample passes through unchanged
        assert bench._extrapolate_serial(70.0, 8, 192) == 70.0 * 24
        assert bench._extrapolate_serial(70.0, 192, 192) == 70.0
        assert bench._extrapolate_serial(70.0, 0, 192) == 70.0
        assert bench._extrapolate_serial(70.0, 200, 192) == 70.0


class TestArtifactSchemaPluginFields:
    """ISSUE 15: the --config plugins fields — fused-vs-sequential
    term speedup, its wall components, and the term-enabled warm
    Score cost."""

    def _line(self, **extra):
        doc = {"metric": "plugins", "value": 1.0, "unit": "x"}
        doc.update(extra)
        return json.dumps(doc)

    def test_valid_plugin_fields_pass(self):
        assert bench._validate_artifact(self._line(
            plugin_terms=3,
            plugin_fused_speedup=2.1,
            plugin_fused_ms=5000.0,
            plugin_oracle_ms=7000.0,
            plugin_base_ms=3200.0,
            plugin_warm_score_ms=450.0,
        )) == []

    def test_malformed_plugin_fields_fail(self):
        assert bench._validate_artifact(self._line(plugin_terms=0))
        assert bench._validate_artifact(self._line(plugin_terms=True))
        assert bench._validate_artifact(self._line(plugin_terms=2.5))
        assert bench._validate_artifact(
            self._line(plugin_fused_speedup=-1.0)
        )
        assert bench._validate_artifact(
            self._line(plugin_fused_speedup=float("nan"))
        )
        assert bench._validate_artifact(self._line(plugin_fused_ms=-2))
        assert bench._validate_artifact(self._line(plugin_oracle_ms="x"))
        assert bench._validate_artifact(self._line(plugin_base_ms=-0.1))
        assert bench._validate_artifact(
            self._line(plugin_warm_score_ms=float("inf"))
        )

    def test_deadline_flush_covers_the_plugins_leg(self):
        # a deadline-flushed plugins artifact must validate: rc=124 on
        # the new config can never again mean "no artifact"
        emitted = []
        d = bench._ArtifactDeadline(
            1000.0, emit=emitted.append, metric="plugins"
        )
        line = d.artifact_line("timeout")
        assert json.loads(line)["metric"] == "plugins"
        assert json.loads(line)["truncated"] is True
        assert bench._validate_artifact(line) == []


class TestArtifactSchemaWaveFields:
    def _line(self, **extra):
        doc = {"metric": "m", "value": 1.0, "unit": "ms"}
        doc.update(extra)
        return json.dumps(doc)

    def test_valid_wave_fields_pass(self):
        assert bench._validate_artifact(self._line(wave=32, rounds=500)) == []
        # the wave stage is best-effort: null rounds is a legal artifact
        assert bench._validate_artifact(self._line(wave=32, rounds=None)) == []

    def test_malformed_wave_fields_fail(self):
        assert bench._validate_artifact(self._line(wave=0))
        assert bench._validate_artifact(self._line(wave=True))
        assert bench._validate_artifact(self._line(wave="32"))
        assert bench._validate_artifact(self._line(rounds=-1))
        assert bench._validate_artifact(self._line(rounds=1.5))


class TestTreeArtifactFields:
    """ISSUE 18: the relay-tree config's artifact fields — depth,
    fan-out amplification, leaf-storm speedup, the zero-resync chaos
    counters and the autoscale verdict — must be archived
    schema-valid; malformed ones must not pass as measurements."""

    def _line(self, **extra):
        doc = {"metric": "tree_converge_wall_ms", "value": 1.0,
               "unit": "ms"}
        doc.update(extra)
        return json.dumps(doc)

    def test_valid_tree_fields_pass(self):
        assert bench._validate_artifact(self._line(
            tree_depth=3, tree_fanout_amplification=2.0,
            tree_read_speedup=0.47, frames_per_wakeup=1.0,
            resyncs_during_failover=0, full_opens_during_failover=0,
            ancestor_switches=1, compressed_fulls=4,
            autoscale_scale_ups=3, autoscale_scale_downs=2,
            autoscale_peak_replicas=4, autoscale_slo_held=True,
        )) == []
        # a truncated (deadline-flushed) artifact may carry nulls
        assert bench._validate_artifact(self._line(
            tree_depth=None, tree_read_speedup=None,
            autoscale_slo_held=None,
        )) == []

    def test_malformed_tree_depth_fails(self):
        assert bench._validate_artifact(self._line(tree_depth=0))
        assert bench._validate_artifact(self._line(tree_depth=True))
        assert bench._validate_artifact(self._line(tree_depth="3"))

    def test_malformed_ratios_fail(self):
        assert bench._validate_artifact(
            self._line(tree_fanout_amplification=-1.0)
        )
        assert bench._validate_artifact(
            self._line(tree_read_speedup=float("nan"))
        )
        assert bench._validate_artifact(
            self._line(frames_per_wakeup=float("inf"))
        )

    def test_malformed_counts_and_verdict_fail(self):
        assert bench._validate_artifact(self._line(ancestor_switches=-1))
        assert bench._validate_artifact(
            self._line(full_opens_during_failover=1.5)
        )
        assert bench._validate_artifact(self._line(compressed_fulls=True))
        assert bench._validate_artifact(
            self._line(autoscale_peak_replicas="4")
        )
        assert bench._validate_artifact(
            self._line(autoscale_slo_held="yes")
        )

    def test_tree_is_a_known_config(self):
        import inspect

        src = inspect.getsource(bench.child_config)
        assert 'config == "tree"' in src


class TestArtifactSchemaSpans:
    """ISSUE 4: BENCH_*.json trajectories carry per-stage span
    summaries; a stage that measured nothing publishes null, and a
    malformed breakdown must not be archived as a measurement."""

    def _line(self, **extra):
        doc = {"metric": "m", "value": 1.0, "unit": "ms"}
        doc.update(extra)
        return json.dumps(doc)

    def test_valid_spans_pass(self):
        assert bench._validate_artifact(self._line(spans={})) == []
        assert bench._validate_artifact(
            self._line(spans={"init": 12.5, "compile": 1500, "wave": None})
        ) == []
        # spans are optional: a span-less artifact stays valid
        assert bench._validate_artifact(self._line()) == []

    def test_malformed_spans_fail(self):
        assert bench._validate_artifact(self._line(spans=[1, 2]))
        assert bench._validate_artifact(self._line(spans={"init": "fast"}))
        assert bench._validate_artifact(self._line(spans={"init": True}))
        assert bench._validate_artifact(self._line(spans={"init": -1.0}))
        assert bench._validate_artifact(
            self._line(spans={"init": float("nan")})
        )
        assert bench._validate_artifact(self._line(spans={"": 1.0}))

    def test_headline_child_seeds_every_stage_null_safe(self):
        """The headline child pre-seeds its span keys so a crashed
        best-effort leg shows as null, not as a missing key a reader
        would misread as 'stage did not exist'."""
        import inspect

        src = inspect.getsource(bench.child)
        for key in ("lowering_probe", "wave_compile", "cpu_native_mt"):
            assert f'"{key}": None' in src


class TestTraceArtifactFields:
    """ISSUE 12: the trace-replay SLO-gate fields must be archived
    well-formed or not at all, and a deadline-killed trace replay must
    still flush one schema-valid ``"truncated": true`` artifact."""

    def _line(self, **extra):
        doc = {"metric": "trace_cycle_p99_ms", "value": 9.9, "unit": "ms"}
        doc.update(extra)
        return json.dumps(doc)

    def _verdict(self, **over):
        doc = {"name": "koord-prod-cycle-p99", "quantile": 0.99,
               "threshold_ms": 2500.0, "observed_ms": 12.5,
               "count": 5, "ok": True}
        doc.update(over)
        return doc

    def test_valid_trace_fields_pass(self):
        assert bench._validate_artifact(self._line(
            trace_events=48,
            trace_parity_checks=49,
            trace_retraces=0,
            trace_seed=0,
            trace_digest="abc123",
            trace_band_p99_ms={"koord-prod": 12.5, "infra": None},
            trace_rpc_p99_ms={"sync": 1.0, "score": 3.0},
            trace_slo=[self._verdict(),
                       self._verdict(ok=False, observed_ms=None)],
            trace_slo_pass=True,
        )) == []
        # every trace field is optional (other configs omit them all)
        assert bench._validate_artifact(self._line()) == []

    def test_malformed_counts_fail(self):
        assert bench._validate_artifact(self._line(trace_events=-1))
        assert bench._validate_artifact(self._line(trace_events=True))
        assert bench._validate_artifact(self._line(trace_retraces=1.5))
        assert bench._validate_artifact(self._line(trace_parity_checks="x"))
        assert bench._validate_artifact(self._line(trace_digest=""))
        assert bench._validate_artifact(self._line(trace_slo_pass="yes"))

    def test_malformed_band_maps_fail(self):
        assert bench._validate_artifact(self._line(trace_band_p99_ms=[1]))
        assert bench._validate_artifact(
            self._line(trace_band_p99_ms={"prod": -1})
        )
        assert bench._validate_artifact(
            self._line(trace_rpc_p99_ms={"sync": float("inf")})
        )
        assert bench._validate_artifact(
            self._line(trace_band_p99_ms={"": 1.0})
        )

    def test_malformed_verdicts_fail(self):
        assert bench._validate_artifact(self._line(trace_slo={}))
        assert bench._validate_artifact(self._line(trace_slo=[[]]))
        assert bench._validate_artifact(
            self._line(trace_slo=[self._verdict(name="")])
        )
        assert bench._validate_artifact(
            self._line(trace_slo=[self._verdict(ok="yes")])
        )
        assert bench._validate_artifact(
            self._line(trace_slo=[self._verdict(quantile=0.0)])
        )
        assert bench._validate_artifact(
            self._line(trace_slo=[self._verdict(quantile=1.5)])
        )
        assert bench._validate_artifact(
            self._line(trace_slo=[self._verdict(threshold_ms=-5)])
        )
        assert bench._validate_artifact(
            self._line(trace_slo=[self._verdict(observed_ms=float("nan"))])
        )

    def test_trace_overhead_and_assembly_fields(self):
        """ISSUE 14: the tracing-overhead delta and the assembly counts
        are schema-validated artifact fields — the overhead may be
        NEGATIVE (run noise) but never below -100 or non-finite, and
        the counts are plain non-negative ints."""
        assert bench._validate_artifact(self._line(
            trace_overhead_p99_pct=2.4,
            assembled_traces=97,
            orphan_spans=0,
        )) == []
        assert bench._validate_artifact(self._line(
            trace_overhead_p99_pct=-3.1,  # traced run won the noise
        )) == []
        assert bench._validate_artifact(
            self._line(trace_overhead_p99_pct=float("nan"))
        )
        assert bench._validate_artifact(
            self._line(trace_overhead_p99_pct=-250.0)
        )
        assert bench._validate_artifact(
            self._line(trace_overhead_p99_pct="small")
        )
        assert bench._validate_artifact(self._line(assembled_traces=-1))
        assert bench._validate_artifact(self._line(assembled_traces=True))
        assert bench._validate_artifact(self._line(orphan_spans=0.5))

    def test_deadline_killed_trace_replay_flushes_truncated_artifact(self):
        """The _ArtifactDeadline flush path covers --config trace: a
        replay hanging past the budget (a wedged UDS server, a compile
        storm) must still put ONE schema-valid truncated artifact on
        stdout, stamped with the trace stage it died in — the
        BENCH_r05 rc=124-no-artifact class must not reopen for the new
        config."""
        emitted, fired = [], []
        now = [0.0]

        def sleep(s):
            now[0] += s

        d = bench._ArtifactDeadline(
            100.0,
            emit=lambda line: emitted.append(line) or True,
            clock=lambda: now[0],
            sleep=sleep,
            on_fire=lambda rc: fired.append(rc),
            metric="trace",  # main() arms the deadline with args.config
        )
        old_stage = bench._PROGRESS["stage"]
        try:
            bench._PROGRESS["stage"] = "config_trace_cpu"
            d.watch()
        finally:
            bench._PROGRESS["stage"] = old_stage
        assert fired == [1] and len(emitted) == 1
        assert bench._validate_artifact(emitted[0]) == []
        doc = json.loads(emitted[0])
        assert doc["truncated"] is True
        assert doc["metric"] == "trace"
        assert "config_trace_cpu" in doc["error"]


class TestChaosTraceArtifactFields:
    """ISSUE 13: the chaos x trace gate fields must be archived
    well-formed or not at all, and a deadline-killed chaos-trace run
    must still flush one schema-valid truncated artifact."""

    def _line(self, **extra):
        doc = {"metric": "chaos_trace_recovery_ms", "value": 6.6,
               "unit": "ms"}
        doc.update(extra)
        return json.dumps(doc)

    def _verdict(self, **over):
        doc = {"name": "recovery-p99", "quantile": 0.99,
               "threshold_ms": 5000.0, "observed_ms": 9.9,
               "count": 1, "ok": True}
        doc.update(over)
        return doc

    def test_valid_chaos_trace_fields_pass(self):
        assert bench._validate_artifact(self._line(
            chaos_trace_events=24,
            chaos_trace_seed=0,
            chaos_trace_errors=4,
            chaos_trace_retraces=0,
            chaos_trace_digest="abc123",
            degraded_replies=1,
            breaker_trips=1,
            recovery_ms=6.6,
            shed_by_band={"koord-free": 96, "none": 2},
            storm_band_p99_ms={"koord-prod": 49.7, "koord-free": None},
            chaos_trace_slo=[self._verdict(),
                             self._verdict(ok=False, observed_ms=None)],
            chaos_trace_slo_pass=True,
        )) == []
        # every chaos-trace field is optional (other configs omit them)
        assert bench._validate_artifact(self._line()) == []

    def test_malformed_counts_fail(self):
        assert bench._validate_artifact(self._line(chaos_trace_events=-1))
        assert bench._validate_artifact(self._line(degraded_replies=1.5))
        assert bench._validate_artifact(self._line(breaker_trips=True))
        assert bench._validate_artifact(self._line(recovery_ms=-1))
        assert bench._validate_artifact(
            self._line(recovery_ms=float("nan"))
        )
        assert bench._validate_artifact(self._line(chaos_trace_digest=""))
        assert bench._validate_artifact(
            self._line(chaos_trace_slo_pass="yes")
        )

    def test_malformed_shed_by_band_fails(self):
        assert bench._validate_artifact(self._line(shed_by_band=[1]))
        assert bench._validate_artifact(
            self._line(shed_by_band={"koord-free": -1})
        )
        assert bench._validate_artifact(
            self._line(shed_by_band={"koord-free": 1.5})
        )
        assert bench._validate_artifact(
            self._line(shed_by_band={"": 3})
        )
        assert bench._validate_artifact(
            self._line(storm_band_p99_ms={"koord-prod": -1})
        )

    def test_malformed_verdicts_fail(self):
        assert bench._validate_artifact(self._line(chaos_trace_slo={}))
        assert bench._validate_artifact(
            self._line(chaos_trace_slo=[self._verdict(name="")])
        )
        assert bench._validate_artifact(
            self._line(chaos_trace_slo=[self._verdict(quantile=0.0)])
        )
        assert bench._validate_artifact(
            self._line(chaos_trace_slo=[self._verdict(threshold_ms=-5)])
        )

    def test_deadline_killed_chaos_trace_flushes_truncated_artifact(self):
        """The _ArtifactDeadline truncated-flush path covers --config
        chaos-trace: a run wedged mid-chaos (a kill that never
        recovers, a hung storm thread) must still put ONE schema-valid
        truncated artifact on stdout stamped with the stage it died
        in."""
        emitted, fired = [], []
        now = [0.0]

        def sleep(s):
            now[0] += s

        d = bench._ArtifactDeadline(
            100.0,
            emit=lambda line: emitted.append(line) or True,
            clock=lambda: now[0],
            sleep=sleep,
            on_fire=lambda rc: fired.append(rc),
            metric="chaos-trace",  # main() arms it with args.config
        )
        old_stage = bench._PROGRESS["stage"]
        try:
            bench._PROGRESS["stage"] = "config_chaos-trace_cpu"
            d.watch()
        finally:
            bench._PROGRESS["stage"] = old_stage
        assert fired == [1] and len(emitted) == 1
        assert bench._validate_artifact(emitted[0]) == []
        doc = json.loads(emitted[0])
        assert doc["truncated"] is True
        assert doc["metric"] == "chaos-trace"
        assert "config_chaos-trace_cpu" in doc["error"]


class TestSparseArtifactFields:
    """ISSUE 16 satellite: the sparse candidate-scoring probe fields
    archive well-formed or not at all — including the one non-numeric
    legal value, the literal ``"OOM"`` that records the dense engine
    REFUSING to allocate its [P, N] tensors at the headline scale."""

    def _line(self, **extra):
        doc = {"metric": "sparse_score_ms", "value": 59.5, "unit": "ms"}
        doc.update(extra)
        return json.dumps(doc)

    def test_full_sparse_artifact_with_dense_oom_is_valid(self):
        assert bench._validate_artifact(self._line(
            sparse_score_ms=59.5, sparse_build_ms=36900.0,
            dense_score_ms="OOM", sparse_speedup=6.4,
            candidate_width=256, candidate_refresh_total=10,
        )) == []

    def test_both_engines_measured_is_valid(self):
        assert bench._validate_artifact(self._line(
            sparse_score_ms=19.7, dense_score_ms=17217.0,
            sparse_speedup=873.9, candidate_width=256,
            candidate_refresh_total=9,
        )) == []

    def test_dense_score_ms_rejects_everything_but_oom_or_number(self):
        assert bench._validate_artifact(self._line(dense_score_ms=None)) == []
        assert bench._validate_artifact(self._line(dense_score_ms=0)) == []
        assert bench._validate_artifact(self._line(dense_score_ms="oom"))
        assert bench._validate_artifact(self._line(dense_score_ms="fast"))
        assert bench._validate_artifact(self._line(dense_score_ms=-1))
        assert bench._validate_artifact(
            self._line(dense_score_ms=float("nan"))
        )

    def test_sparse_timings_must_be_finite_nonneg(self):
        assert bench._validate_artifact(self._line(sparse_score_ms=-0.1))
        assert bench._validate_artifact(
            self._line(sparse_build_ms=float("inf"))
        )
        assert bench._validate_artifact(self._line(sparse_speedup=-2.0))
        assert bench._validate_artifact(
            self._line(sparse_speedup=float("nan"))
        )

    def test_candidate_width_must_be_a_positive_int(self):
        assert bench._validate_artifact(self._line(candidate_width=256)) == []
        assert bench._validate_artifact(self._line(candidate_width=0))
        assert bench._validate_artifact(self._line(candidate_width=True))
        assert bench._validate_artifact(self._line(candidate_width=64.0))

    def test_candidate_refresh_total_must_be_a_nonneg_int(self):
        assert bench._validate_artifact(
            self._line(candidate_refresh_total=0)
        ) == []
        assert bench._validate_artifact(
            self._line(candidate_refresh_total=-1)
        )
        assert bench._validate_artifact(
            self._line(candidate_refresh_total=True)
        )
        assert bench._validate_artifact(
            self._line(candidate_refresh_total=9.5)
        )

    def test_deadline_killed_sparse_run_flushes_truncated_artifact(self):
        """A sparse run wedged mid-build (the blocked sweep at the
        headline node count is the slow stage) still puts ONE
        schema-valid truncated artifact on stdout stamped with the
        stage it died in."""
        emitted, fired = [], []
        now = [0.0]

        def sleep(s):
            now[0] += s

        d = bench._ArtifactDeadline(
            100.0,
            emit=lambda line: emitted.append(line) or True,
            clock=lambda: now[0],
            sleep=sleep,
            on_fire=lambda rc: fired.append(rc),
            metric="sparse",  # main() arms it with args.config
        )
        old_stage = bench._PROGRESS["stage"]
        try:
            bench._PROGRESS["stage"] = "config_sparse_cpu"
            d.watch()
        finally:
            bench._PROGRESS["stage"] = old_stage
        assert fired == [1] and len(emitted) == 1
        assert bench._validate_artifact(emitted[0]) == []
        doc = json.loads(emitted[0])
        assert doc["truncated"] is True
        assert doc["metric"] == "sparse"
        assert "config_sparse_cpu" in doc["error"]


class TestArtifactSchemaDevprofFields:
    """ISSUE 19: the device-time-truth fields — the launch ledger's
    compile-vs-device split, the attributed backend, the sampling
    overhead delta and the parent's TPU-probe verdict.  The archive
    rule is unchanged: malformed values must not be archived, nulls
    (probe failed / ledger off) always pass."""

    def _line(self, **extra):
        doc = {"metric": "m", "value": 1.0, "unit": "ms"}
        doc.update(extra)
        return json.dumps(doc)

    def test_well_formed_devprof_fields_pass(self):
        assert bench._validate_artifact(self._line(
            devprof_backend="cpu", devprof_compiles=7,
            devprof_compile_ms_total=412.6, devprof_device_score_us=83.2,
            devprof_flops_per_launch=1.2e9, devprof_overhead_p99_pct=0.7,
            tpu_probe="unreachable",
        )) == []

    def test_all_null_devprof_fields_pass(self):
        assert bench._validate_artifact(self._line(
            devprof_backend=None, devprof_compiles=None,
            devprof_compile_ms_total=None, devprof_device_score_us=None,
            devprof_flops_per_launch=None, devprof_overhead_p99_pct=None,
            tpu_probe=None,
        )) == []

    def test_malformed_compile_and_device_fields_fail(self):
        assert bench._validate_artifact(
            self._line(devprof_compile_ms_total=-1)
        )
        assert bench._validate_artifact(
            self._line(devprof_compile_ms_total=float("nan"))
        )
        assert bench._validate_artifact(
            self._line(devprof_device_score_us=float("inf"))
        )
        assert bench._validate_artifact(
            self._line(devprof_device_score_us="83")
        )
        assert bench._validate_artifact(
            self._line(devprof_flops_per_launch=-2.0)
        )

    def test_backend_and_compiles_shape(self):
        assert bench._validate_artifact(self._line(devprof_backend=""))
        assert bench._validate_artifact(self._line(devprof_backend=3))
        assert bench._validate_artifact(self._line(devprof_compiles=-1))
        assert bench._validate_artifact(self._line(devprof_compiles=True))
        assert bench._validate_artifact(self._line(devprof_compiles=2.5))

    def test_overhead_delta_rule_matches_trace_overhead(self):
        # negative is legitimate run noise; below -100 is fabricated
        assert bench._validate_artifact(
            self._line(devprof_overhead_p99_pct=-3.0)
        ) == []
        assert bench._validate_artifact(
            self._line(devprof_overhead_p99_pct=-101.0)
        )
        assert bench._validate_artifact(
            self._line(devprof_overhead_p99_pct=float("nan"))
        )

    def test_tpu_probe_field_shape(self):
        assert bench._validate_artifact(self._line(tpu_probe="live")) == []
        assert bench._validate_artifact(
            self._line(tpu_probe="live-then-lost")
        ) == []
        assert bench._validate_artifact(self._line(tpu_probe=""))
        assert bench._validate_artifact(self._line(tpu_probe=7))

    def test_stamp_tpu_probe_rides_the_artifact(self):
        # the r04/r05 fix: the parent's probe verdict is stamped onto
        # whatever the child printed, and an unparseable line passes
        # through untouched for the validator to reject downstream
        line = bench._stamp_tpu_probe(self._line(), "unreachable")
        doc = json.loads(line)
        assert doc["tpu_probe"] == "unreachable"
        assert bench._validate_artifact(line) == []
        assert bench._stamp_tpu_probe("not json{", "live") == "not json{"
        assert bench._stamp_tpu_probe(None, "live") is None

    def test_deadline_flush_still_valid_with_devprof_schema(self):
        """The truncated-flush line must stay schema-valid now that the
        validator knows the devprof fields (the deadline artifact
        carries none of them — all-absent must read as all-null)."""
        emitted, fired = [], []
        now = [0.0]

        def sleep(s):
            now[0] += s

        d = bench._ArtifactDeadline(
            100.0,
            emit=lambda line: emitted.append(line) or True,
            clock=lambda: now[0],
            sleep=sleep,
            on_fire=lambda rc: fired.append(rc),
        )
        old_stage = bench._PROGRESS["stage"]
        try:
            bench._PROGRESS["stage"] = "devprof_storm"
            d.watch()
        finally:
            bench._PROGRESS["stage"] = old_stage
        assert fired == [1] and len(emitted) == 1
        assert bench._validate_artifact(emitted[0]) == []
        doc = json.loads(emitted[0])
        assert doc["truncated"] is True
        assert "devprof_storm" in doc["error"]


class TestArtifactSchemaColdstartFields:
    """ISSUE 20: the cold-path economics fields — the coldstart leg's
    boot walls and prewarm stats, the failover warm-restart
    replay/compile split, and the autoscaler's spawn->ready wall.
    Nulls always pass (leg not run / attribution unavailable);
    malformed values must not be archived."""

    def _line(self, **extra):
        doc = {"metric": "m", "value": 1.0, "unit": "ms"}
        doc.update(extra)
        return json.dumps(doc)

    def test_well_formed_coldstart_fields_pass(self):
        assert bench._validate_artifact(self._line(
            cold_start_ms=1995.1, warm_cache_start_ms=840.3,
            cold_start_speedup=2.37, prewarm_ms=530.3,
            prewarm_signatures=12, prewarm_compiled=11,
            prewarm_compile_ms=263.4, cold_build_serial_ms=37000.0,
            cold_build_ms=14800.0, cold_build_speedup=2.5,
            build_nodes=2097152,
        )) == []

    def test_all_null_coldstart_fields_pass(self):
        assert bench._validate_artifact(self._line(
            cold_start_ms=None, warm_cache_start_ms=None,
            cold_start_speedup=None, prewarm_ms=None,
            prewarm_signatures=None, prewarm_compiled=None,
            prewarm_compile_ms=None, cold_build_serial_ms=None,
            cold_build_ms=None, cold_build_speedup=None,
            build_nodes=None,
        )) == []

    def test_malformed_boot_walls_fail(self):
        assert bench._validate_artifact(self._line(cold_start_ms=-1))
        assert bench._validate_artifact(
            self._line(warm_cache_start_ms=float("nan"))
        )
        assert bench._validate_artifact(
            self._line(cold_start_speedup=float("inf"))
        )
        assert bench._validate_artifact(self._line(prewarm_ms=-0.5))

    def test_malformed_build_timings_fail(self):
        assert bench._validate_artifact(
            self._line(cold_build_serial_ms=-3.0)
        )
        assert bench._validate_artifact(
            self._line(cold_build_ms=float("nan"))
        )
        assert bench._validate_artifact(
            self._line(cold_build_speedup=-1.0)
        )

    def test_prewarm_counts_must_be_nonneg_ints(self):
        for key in ("prewarm_signatures", "prewarm_compiled",
                    "build_nodes"):
            assert bench._validate_artifact(self._line(**{key: 0})) == []
            assert bench._validate_artifact(self._line(**{key: -1}))
            assert bench._validate_artifact(self._line(**{key: True}))
            assert bench._validate_artifact(self._line(**{key: 2.5}))

    def test_failover_restart_split_fields(self):
        assert bench._validate_artifact(self._line(
            restart_replay_ms=12.4, restart_compile_ms=310.9
        )) == []
        assert bench._validate_artifact(self._line(
            restart_replay_ms=None, restart_compile_ms=None
        )) == []
        assert bench._validate_artifact(
            self._line(restart_replay_ms=-1.0)
        )
        assert bench._validate_artifact(
            self._line(restart_compile_ms=float("nan"))
        )

    def test_spawn_to_ready_field(self):
        assert bench._validate_artifact(
            self._line(spawn_to_ready_ms=41.2)
        ) == []
        assert bench._validate_artifact(
            self._line(spawn_to_ready_ms=None)
        ) == []
        assert bench._validate_artifact(
            self._line(spawn_to_ready_ms=-0.1)
        )
        assert bench._validate_artifact(
            self._line(spawn_to_ready_ms=float("inf"))
        )

    def test_coldstart_is_a_dispatchable_config(self):
        # the driver archives per-config: a choice missing from the
        # inline parser would make the leg silently unrunnable
        import inspect

        src = inspect.getsource(bench.main)
        assert '"coldstart"' in src
        assert "--coldstart-server" in src
