"""solver/wave.py unit tests: the packed-key encode/decode and the
in-wave certification resolver, exercised directly (the full-cycle
bit-parity lives in tests/test_parity_fuzz.py / test_parallel.py — here
the SHARED primitives both the shard_map and single-chip paths consume
are pinned in isolation, so a refactor of either path cannot silently
fork the math)."""

import jax.numpy as jnp
import numpy as np
import pytest

from koordinator_tpu.config import CycleConfig
from koordinator_tpu.model import resources as res
from koordinator_tpu.solver import wave as wv
from koordinator_tpu.solver.greedy import step_feasible_scores

R = res.NUM_RESOURCES


class TestPackedKeys:
    def test_roundtrip(self):
        rng = np.random.RandomState(0)
        N = 97
        scores = jnp.asarray(rng.randint(-5000, 5000, 64), jnp.int64)
        idx = jnp.asarray(rng.randint(0, N, 64), jnp.int64)
        keys = wv.pack_keys(scores, jnp.ones(64, bool), idx, N)
        got_s, got_i = wv.decode_key(keys, N)
        np.testing.assert_array_equal(np.asarray(got_s), np.asarray(scores))
        np.testing.assert_array_equal(np.asarray(got_i), np.asarray(idx))
        assert bool(wv.score_feasible(got_s).all())

    def test_infeasible_slots_decode_as_sentinel(self):
        N = 16
        keys = wv.pack_keys(
            jnp.asarray([100, 100], jnp.int64),
            jnp.asarray([True, False]),
            jnp.asarray([3, 3], jnp.int64),
            N,
        )
        s, i = wv.decode_key(keys, N)
        assert bool(wv.score_feasible(s[0]))
        assert not bool(wv.score_feasible(s[1]))
        assert int(i[1]) == 3  # the index term survives the sentinel
        assert int(keys[1]) <= int(wv.sentinel_threshold(N))
        assert int(keys[0]) > int(wv.sentinel_threshold(N))

    def test_ordering_is_score_desc_then_index_asc(self):
        N = 32
        feas = jnp.ones((), bool)

        def key(s, i):
            return int(
                wv.pack_keys(
                    jnp.int64(s), feas, jnp.int64(i), N
                )
            )

        assert key(10, 5) > key(9, 0)  # higher score wins
        assert key(10, 2) > key(10, 3)  # equal score: lower index wins
        assert key(0, 0) > key(-1, 0)
        # uniqueness: distinct (score, idx) -> distinct keys
        seen = {key(s, i) for s in range(-3, 4) for i in range(N)}
        assert len(seen) == 7 * N


def _cand(W, M, gid, alloc_rows, nreq_rows):
    """Candidate-row dict for resolve_wave's k_M path (zeros elsewhere)."""
    return dict(
        gid=jnp.asarray(gid, jnp.int64),
        alloc=jnp.asarray(alloc_rows, jnp.int64),
        nreq=jnp.asarray(nreq_rows, jnp.int64),
        nest=jnp.zeros((W, M, R), jnp.int64),
        usage=jnp.zeros((W, M, R), jnp.int64),
        ok=jnp.ones((W, M), bool),
        fresh=jnp.ones((W, M), bool),
        xval=jnp.zeros((W, M), jnp.int64),
        xfeas=jnp.ones((W, M), bool),
    )


def _frozen_keys(cand, cfg, n_total, preq, psreq, pest, qrt, qlim, quse):
    """Frozen per-pod candidate keys through the same step semantics the
    resolver re-keys with (what the wave paths capture before a round)."""
    rows = []
    for w in range(preq.shape[0]):
        feas, total = step_feasible_scores(
            cand["nreq"][w], cand["nest"][w], quse, cand["alloc"][w],
            cand["usage"][w], cand["fresh"][w], cand["ok"][w],
            preq[w], psreq[w], pest[w], jnp.int32(-1), jnp.bool_(True),
            qrt, qlim, cfg,
        )
        rows.append(wv.pack_keys(total, feas, cand["gid"][w], n_total))
    return jnp.stack(rows)


def _vec(cpu):
    v = np.zeros(R, np.int64)
    v[0] = cpu
    return v


class TestResolveWave:
    CFG = CycleConfig(enable_loadaware=False)

    def _quota_free(self):
        qrt = jnp.zeros((1, R), jnp.int64)
        qlim = jnp.zeros((1, R), bool)
        quse = jnp.zeros((1, R), jnp.int64)
        return qrt, qlim, quse

    def _resolve(self, cand_key, cand, preq, qids=None, wvalid=None,
                 quota=None):
        W = preq.shape[0]
        qrt, qlim, quse = quota if quota is not None else self._quota_free()
        return wv.resolve_wave(
            cand_key,
            cand=cand,
            universe=None,
            preq_wave=preq,
            pest_wave=jnp.zeros_like(preq),
            psreq_wave=preq,
            pqid_wave=(
                jnp.asarray(qids, jnp.int32)
                if qids is not None
                else jnp.full((W,), -1, jnp.int32)
            ),
            pvalid_wave=jnp.ones((W,), bool),
            pprod_wave=jnp.zeros((W,), bool),
            wvalid=(
                jnp.asarray(wvalid)
                if wvalid is not None
                else jnp.ones((W,), bool)
            ),
            qrt=qrt,
            qlim=qlim,
            quse=quse,
            cfg=self.CFG,
            n_total=4,
            prod_sensitive=False,
        )

    def test_consumed_candidate_ends_the_commit_prefix(self):
        """Two pods, both frozen onto the same one-pod-sized node: pod 0
        commits, pod 1's only candidate fills in-wave and its k_M is
        above the sentinel — it must END the prefix (feasible nodes
        below k_M may remain), never commit -1."""
        W, M = 2, 1
        cand = _cand(
            W, M,
            gid=[[0], [0]],
            alloc_rows=[[_vec(10)], [_vec(10)]],
            nreq_rows=np.zeros((W, M, R)),
        )
        preq = jnp.asarray([_vec(8), _vec(8)], jnp.int64)
        qrt, qlim, quse = self._quota_free()
        cand_key = _frozen_keys(
            cand, self.CFG, 4, preq, preq, jnp.zeros_like(preq),
            qrt, qlim, quse,
        )
        choices, committed, done, _, ncommit = self._resolve(
            cand_key, cand, preq
        )
        assert np.asarray(choices).tolist() == [0, -1]
        assert np.asarray(committed).tolist() == [True, False]
        assert np.asarray(done).tolist() == [True, False]
        assert int(ncommit) == 1

    def test_disjoint_candidates_commit_the_whole_wave(self):
        W, M = 2, 1
        cand = _cand(
            W, M,
            gid=[[0], [1]],
            alloc_rows=[[_vec(10)], [_vec(10)]],
            nreq_rows=np.zeros((W, M, R)),
        )
        preq = jnp.asarray([_vec(8), _vec(8)], jnp.int64)
        qrt, qlim, quse = self._quota_free()
        cand_key = _frozen_keys(
            cand, self.CFG, 4, preq, preq, jnp.zeros_like(preq),
            qrt, qlim, quse,
        )
        choices, committed, done, _, ncommit = self._resolve(
            cand_key, cand, preq
        )
        assert np.asarray(choices).tolist() == [0, 1]
        assert np.asarray(done).tolist() == [True, True]
        assert int(ncommit) == 2

    def test_quota_blocked_pod_commits_unschedulable_in_wave(self):
        """Quota admission is node-invariant: a blocked pod is an exact
        -1 commit (the prefix continues past it), including blocks
        created by an EARLIER in-wave commit on the same quota."""
        W, M = 2, 1
        cand = _cand(
            W, M,
            gid=[[0], [1]],
            alloc_rows=[[_vec(100)], [_vec(100)]],
            nreq_rows=np.zeros((W, M, R)),
        )
        preq = jnp.asarray([_vec(8), _vec(8)], jnp.int64)
        # quota runtime fits ONE pod's cpu; both pods share quota 0
        qrt = jnp.asarray([_vec(10)], jnp.int64)
        qlim = jnp.asarray([_vec(1) > 0], bool).reshape(1, R)
        quse = jnp.zeros((1, R), jnp.int64)
        cand_key = _frozen_keys(
            cand, self.CFG, 4, preq, preq, jnp.zeros_like(preq),
            qrt, qlim, quse,
        )
        choices, committed, done, quse_new, ncommit = self._resolve(
            cand_key, cand, preq, qids=[0, 0], quota=(qrt, qlim, quse)
        )
        assert np.asarray(choices).tolist() == [0, -1]
        assert np.asarray(done).tolist() == [True, True]  # both exact
        assert int(ncommit) == 2
        assert int(np.asarray(quse_new)[0, 0]) == 8  # one commit charged

    def test_padding_lane_commits_without_taking_a_node(self):
        W, M = 2, 1
        cand = _cand(
            W, M,
            gid=[[0], [1]],
            alloc_rows=[[_vec(100)], [_vec(100)]],
            nreq_rows=np.zeros((W, M, R)),
        )
        preq = jnp.asarray([_vec(8), _vec(8)], jnp.int64)
        qrt, qlim, quse = self._quota_free()
        cand_key = _frozen_keys(
            cand, self.CFG, 4, preq, preq, jnp.zeros_like(preq),
            qrt, qlim, quse,
        )
        choices, committed, done, _, ncommit = self._resolve(
            cand_key, cand, preq, wvalid=[True, False]
        )
        assert np.asarray(choices).tolist() == [0, -1]
        assert np.asarray(committed).tolist() == [True, False]
        assert int(ncommit) == 2

    def test_most_allocated_requires_the_universe(self):
        with pytest.raises(ValueError, match="universe"):
            wv.resolve_wave(
                jnp.zeros((1, 1), jnp.int64),
                cand=_cand(1, 1, [[0]], [[_vec(10)]], np.zeros((1, 1, R))),
                universe=None,
                preq_wave=jnp.zeros((1, R), jnp.int64),
                pest_wave=jnp.zeros((1, R), jnp.int64),
                psreq_wave=jnp.zeros((1, R), jnp.int64),
                pqid_wave=jnp.full((1,), -1, jnp.int32),
                pvalid_wave=jnp.ones((1,), bool),
                pprod_wave=jnp.zeros((1,), bool),
                wvalid=jnp.ones((1,), bool),
                qrt=jnp.zeros((1, R), jnp.int64),
                qlim=jnp.zeros((1, R), bool),
                quse=jnp.zeros((1, R), jnp.int64),
                cfg=CycleConfig(fit_scoring_strategy="MostAllocated"),
                n_total=4,
                prod_sensitive=False,
            )


class TestWaveAssignKnobs:
    def test_rejects_degenerate_knobs(self):
        from koordinator_tpu.solver import wave_assign

        with pytest.raises(ValueError, match="must be >= 1"):
            wave_assign(None, wave=0)
        with pytest.raises(ValueError, match="must be >= 1"):
            wave_assign(None, top_m=0)

    def test_run_cycle_falls_back_to_scan_on_oversized_extra_scores(self):
        """run_cycle never raises for in-contract inputs: extra_scores
        beyond the packed-key range (>= 2^31) must take the
        bit-identical scan path instead of tripping wave_assign's
        magnitude guard."""
        from koordinator_tpu.harness import generators
        from koordinator_tpu.model import encode_snapshot
        from koordinator_tpu.solver import greedy_assign, run_cycle

        n, p, g, q = generators.loadaware_joint(seed=9, pods=24, nodes=6)
        snap = encode_snapshot(n, p, g, q)
        P = snap.pods.capacity
        N = snap.nodes.allocatable.shape[0]
        big = jnp.full((P, N), 2**31, jnp.int64)
        got = run_cycle(snap, CycleConfig(wave=8), extra_scores=big)
        assert got.path == "scan"
        want = greedy_assign(snap, extra_scores=big)
        np.testing.assert_array_equal(
            np.asarray(got.assignment), np.asarray(want.assignment)
        )

    def test_knobs_default_from_the_cycle_config(self):
        from koordinator_tpu.harness import generators
        from koordinator_tpu.model import encode_snapshot
        from koordinator_tpu.solver import greedy_assign, wave_assign

        n, p, g, q = generators.loadaware_joint(seed=9, pods=48, nodes=12)
        snap = encode_snapshot(n, p, g, q)
        cfg = CycleConfig(wave=8, top_m=2)
        got = wave_assign(snap, cfg)  # no explicit knobs
        want = greedy_assign(snap, cfg)
        np.testing.assert_array_equal(
            np.asarray(got.assignment), np.asarray(want.assignment)
        )
        rounds = int(np.asarray(got.rounds))
        assert 1 <= rounds < snap.pods.capacity
        assert got.path == "wave"
