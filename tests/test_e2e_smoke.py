"""Cluster-in-a-process e2e smoke: every binary's role, wired together.

The reference's e2e layer runs kind clusters (SURVEY §4); this is the
in-process equivalent smoke: koord-manager computes batch overcommit,
the webhook mutates a BE pod onto batch resources, koord-scheduler
places the mix through the bridge seam, a reservation goes
Pending → scheduled → Available, and koord-descheduler's LowNodeLoad
evicts from the hot node through the MigrationController.
"""

import time

import pytest

from koordinator_tpu.bridge.codegen import pb2
from koordinator_tpu.harness.golden import build_sync_request
from koordinator_tpu.manager.profile import mutate_by_profiles
from koordinator_tpu.manager.server import ClusterView, ManagerServer


@pytest.fixture()
def cluster(tmp_path):
    nodes = [
        {
            "name": f"n{i}",
            "allocatable": {"cpu": "16000m", "memory": "65536Mi", "pods": 110},
            "usage": {"cpu": f"{1000 * (i + 1)}m", "memory": "8192Mi"},
            "labels": {},
        }
        for i in range(4)
    ]
    prod_pods = [
        {
            "name": f"prod-{i}",
            "node": f"n{i % 4}",
            "requests": {"cpu": "2000m", "memory": "4096Mi"},
            "priority_class": "koord-prod",
            "priority": 9500,
        }
        for i in range(8)
    ]
    metrics = {
        n["name"]: {
            "system_usage": {"cpu": "500m", "memory": "1024Mi"},
            "pod_metrics": {},
            "update_time": time.time(),
        }
        for n in nodes
    }
    return nodes, prod_pods, metrics


def test_full_stack_smoke(tmp_path, cluster):
    nodes, prod_pods, metrics = cluster

    # ---- koord-manager: batch overcommit -> node extended resources ----
    view = ClusterView(
        nodes_fn=lambda: nodes,
        pods_fn=lambda: prod_pods,
        node_metrics_fn=lambda: metrics,
    )
    manager = ManagerServer(
        view, lease_path=str(tmp_path / "mgr.lease"), resync_seconds=3600
    )
    manager.reconcile_once()
    batch = view.node_extended_resources["n0"]
    assert batch.get("kubernetes.io/batch-cpu", 0) > 0
    for nd in nodes:
        ext = view.node_extended_resources[nd["name"]]
        # as_extended_resources emits axis units ready for re-parse:
        # batch-cpu a bare milli int, batch-memory an "NMi" string
        nd["allocatable"] = {**nd["allocatable"], **ext}

    # ---- webhook: a BE pod is mutated onto batch resources ----
    profiles = [
        {
            "name": "be-profile",
            "spec": {
                "selector": {"matchLabels": {"app": "batch-job"}},
                "labels": {"koordinator.sh/qosClass": "BE"},
                "priorityClassName": "koord-batch",
            },
        }
    ]
    be_pod = {
        "name": "be-0",
        "labels": {"app": "batch-job"},
        "requests": {"cpu": "1000m", "memory": "2048Mi"},
        "priority": 5500,
    }
    mutated = mutate_by_profiles(be_pod, profiles)
    # resource translation moved the BE pod onto batch resources
    assert "kubernetes.io/batch-cpu" in mutated["requests"]

    # ---- koord-scheduler: the mix placed through the bridge seam ----
    from koordinator_tpu.scheduler.server import SchedulerServer

    sched = SchedulerServer(
        lease_path=str(tmp_path / "sched.lease"),
        uds_path=str(tmp_path / "scorer.sock"),
        enable_grpc=False,
    )
    sched.elector.is_leader = True  # unit-style: elected synchronously

    pending = [dict(p, node=None) for p in prod_pods[:4]] + [mutated]
    req, _ = build_sync_request(nodes, pending, [], [])
    sid = sched.servicer.sync(req).snapshot_id
    reply = sched.servicer.assign(pb2.AssignRequest(snapshot_id=sid))
    assignment = list(reply.assignment)
    assert len(assignment) == len(pending)
    assert all(a >= 0 for a in assignment), "everything must place"
    assert reply.path in ("pallas", "scan")

    # ---- koordlet -> scheduler NRT chain: each node's koordlet publishes
    # NodeResourceTopology from its (fake) sysfs, and the scheduler's
    # NodeNUMAResource zone extras are built from the PUBLISHED reports,
    # not hand-built fixtures (reference states_noderesourcetopology.go
    # producing what topology_options.go consumes) ----
    import jax.numpy as jnp
    import numpy as np

    from koordinator_tpu.koordlet.statesinformer import (
        NodeTopoReporter,
        StatesInformer,
        zones_from_node_topos,
    )
    from koordinator_tpu.koordlet.sysfs import CgroupVersion, SysFS
    from koordinator_tpu.model import encode_snapshot
    from koordinator_tpu.model.topology import encode_zones
    from koordinator_tpu.scheduler.framework import (
        CycleContext,
        FrameworkExtender,
    )
    from koordinator_tpu.ops.numa import POLICY_SINGLE_NUMA_NODE
    from koordinator_tpu.scheduler.plugins import NodeNUMAResourcePlugin
    from tests.test_statesinformer_producers import write_sysfs_topology

    published = []
    for i, nd in enumerate(nodes[:2]):
        root = str(tmp_path / f"host-{i}")
        # host 0: 2 NUMA zones x 4 cores; host 1: small 1-core zones
        write_sysfs_topology(
            root, numa_nodes=2, cores_per_node=4 if i == 0 else 1, threads=2
        )
        informer = StatesInformer()
        informer.register_plugin(
            NodeTopoReporter(
                SysFS(root=root, cgroup_version=CgroupVersion.V1),
                informer,
                node_name=nd["name"],
            )
        )
        informer.sync_plugins(time.time())
        published.append(informer.get_node_topo())
    assert all(t.get("zones") for t in published)

    numa_snap = encode_snapshot(
        nodes[:2],
        [{"name": "numa-pod", "requests": {"cpu": "6000m", "memory": "1024Mi"}}],
        [],
        [],
    )
    zones = encode_zones(
        zones_from_node_topos(published), node_bucket=numa_snap.nodes.capacity
    )
    policy = jnp.full(
        (numa_snap.nodes.capacity,), POLICY_SINGLE_NUMA_NODE, jnp.int32
    )
    fx = FrameworkExtender([NodeNUMAResourcePlugin()])
    numa_result = fx.run_cycle(
        CycleContext(
            snapshot=numa_snap, extras={"zones": zones, "numa_policy": policy}
        )
    )
    # the 6-core pod fits a published 8-cpu zone on host 0; host 1's
    # 2-cpu zones cannot hold it under single-numa admission
    assert int(np.asarray(numa_result.assignment)[0]) == 0

    # ---- reservation: Pending -> scheduled -> Available ----
    from koordinator_tpu.scheduler.reservation_controller import (
        AVAILABLE,
        Reservation,
        ReservationController,
    )

    rc = ReservationController(clock=lambda: 0.0)
    rc.create(
        Reservation(
            name="web-reserve",
            requests={"cpu": "4000m", "memory": "8192Mi"},
            owners=[{"label_selector": {"app": "web"}}],
            ttl_seconds=None,
        )
    )
    reserve_pods = rc.pending_reserve_pods()
    req2, _ = build_sync_request(nodes, reserve_pods, [], [])
    sv2 = sched.servicer
    sid2 = sv2.sync(req2).snapshot_id
    r2 = sv2.assign(pb2.AssignRequest(snapshot_id=sid2))
    chosen = list(r2.assignment)[0]
    assert chosen >= 0
    rc.on_reserve_pod_assigned("web-reserve", nodes[chosen]["name"])
    assert rc.reservations["web-reserve"].phase == AVAILABLE

    # ---- koord-descheduler: hot node rebalanced via migration ----
    from koordinator_tpu.descheduler.evictions import PodEvictor
    from koordinator_tpu.descheduler.migration import (
        MigrationController,
        MigrationControllerArgs,
    )
    from koordinator_tpu.descheduler.lownodeload import (
        LowNodeLoadArgs,
        NodePool,
    )
    from koordinator_tpu.descheduler.runtime import (
        Descheduler,
        DeschedulerProfile,
        PluginSet,
    )

    nodes[0]["usage"] = {"cpu": "15000m", "memory": "20480Mi"}
    nodes[0]["pods"] = [
        {
            "name": f"victim-{i}",
            "namespace": "default",
            "requests": {"cpu": "3000m", "memory": "4096Mi"},
            "usage": {"cpu": "3000m", "memory": "4096Mi"},
            "priority": 5000,
            "owner_references": [{"kind": "ReplicaSet", "name": "rs"}],
        }
        for i in range(4)
    ]
    for nd in nodes[1:]:
        nd["pods"] = []
    evictor = PodEvictor()
    migration = MigrationController(
        args=MigrationControllerArgs(default_job_mode="EvictDirectly"),
        evict=lambda pod: evictor.evict(pod, pod.get("node", ""), reason="m"),
    )
    profile = DeschedulerProfile(
        plugins=PluginSet(balance=["LowNodeLoad"]),
        plugin_config={
            "LowNodeLoad": LowNodeLoadArgs(
                node_pools=[
                    NodePool(
                        low_thresholds={"cpu": 30, "memory": 30},
                        high_thresholds={"cpu": 70, "memory": 70},
                        consecutive_abnormalities=1,
                    )
                ]
            )
        },
    )
    d = Descheduler(
        [profile], nodes_fn=lambda: nodes, evictor=evictor, migration=migration
    )
    status = d.descheduler_once()
    assert status.ok
    assert evictor.total_evicted() >= 1
    assert all(r.node == "n0" for r in evictor.evicted)


def test_reservation_first_migration(cluster):
    """The reference's ReservationFirst migration mode end to end
    (controllers/migration/controller.go:241): the MigrationController
    creates a Reservation for the victim, waits for the scheduler to bind
    it, and only then evicts — so the pod's capacity is guaranteed at the
    destination before the disruption."""
    from koordinator_tpu.bridge.server import ScorerServicer
    from koordinator_tpu.descheduler.evictions import PodEvictor
    from koordinator_tpu.descheduler.migration import (
        MigrationController,
        MigrationControllerArgs,
        MigrationJob,
    )
    from koordinator_tpu.scheduler.reservation_controller import (
        AVAILABLE,
        Reservation,
        ReservationController,
    )

    nodes, prod_pods, metrics = cluster
    evictor = PodEvictor()
    rc = ReservationController(clock=lambda: 0.0)
    servicer = ScorerServicer()

    def create_reservation(job: MigrationJob):
        """Migration's reservation factory: creates the Reservation only;
        the scheduler binds it on a LATER cycle (async, like the real
        apiserver flow)."""
        name = f"migrate-{job.pod['name']}"
        rc.create(
            Reservation(
                name=name,
                requests=dict(job.pod.get("requests") or {}),
                ttl_seconds=None,
            )
        )
        return name

    def schedule_pending_reservations(exclude_node):
        # the scheduler cycle places pending reserve pods (source node
        # taken out of the candidate set, like the reference's
        # anti-affinity to the source)
        candidates = [nd for nd in nodes if nd["name"] != exclude_node]
        reserve_pods = rc.pending_reserve_pods()
        if not reserve_pods:
            return
        req, _ = build_sync_request(candidates, reserve_pods, [], [])
        sid = servicer.sync(req).snapshot_id
        reply = servicer.assign(pb2.AssignRequest(snapshot_id=sid))
        for pod, chosen in zip(reserve_pods, reply.assignment):
            if chosen >= 0:
                rc.on_reserve_pod_assigned(
                    pod["annotations"][
                        "scheduling.koordinator.sh/reservation-name"
                    ],
                    candidates[chosen]["name"],
                )

    migration = MigrationController(
        args=MigrationControllerArgs(default_job_mode="ReservationFirst"),
        create_reservation=create_reservation,
        reservation_bound=lambda name: rc.reservations[name].phase
        == AVAILABLE,
        evict=lambda pod: evictor.evict(
            pod, pod.get("node", ""), reason="reservation-first migration"
        ),
    )

    victim = {
        "name": "victim-0",
        "namespace": "default",
        "node": "n0",
        "requests": {"cpu": "2000m", "memory": "4096Mi"},
    }
    migration.submit(
        MigrationJob(name="mj-victim-0", pod=victim, creation_time=0.0)
    )
    # tick 1: the reservation exists but is NOT yet bound — the job must
    # WAIT, not evict (controller.go:587 wait-for-bound)
    migration.reconcile(now=1.0)
    job = migration.jobs["mj-victim-0"]
    assert job.phase == "Running"
    assert job.reason == "WaitForReservationBound"
    assert evictor.total_evicted() == 0

    # the scheduler binds the reserve pod between ticks
    schedule_pending_reservations(exclude_node="n0")

    # tick 2: bound -> evict -> Succeeded
    migration.reconcile(now=2.0)
    job = migration.jobs["mj-victim-0"]
    assert job.phase == "Succeeded", (job.phase, job.reason)
    # the reservation was bound on a DIFFERENT node before the eviction
    r = rc.reservations[job.reservation_name]
    assert r.phase == AVAILABLE and r.node != "n0"
    assert [e.pod for e in evictor.evicted] == ["victim-0"]
