"""Chaos fault-injection acceptance (ISSUE 11): the tier survives a
leader kill (journal warm-restart AND follower promotion) under
injected frame drop/corrupt/truncate/reorder, ending byte-identical to
the unfaulted single-daemon oracle with zero torn snapshots — and the
warm path holds zero jit cache misses after recovery.
"""

import numpy as np
import pytest

from koordinator_tpu.bridge.codegen import pb2
from koordinator_tpu.bridge.state import numpy_to_tensor
from koordinator_tpu.harness import generators
from koordinator_tpu.harness.chaos import (
    ChaosTier,
    FaultPlan,
    fail_next_launch,
    flat_score_bytes,
)
from koordinator_tpu.harness.golden import build_sync_request
from koordinator_tpu.model import resources as res
from koordinator_tpu.replication.admission import ResourceExhausted


def _tiny_sync(pods=32, nodes=8, seed=3):
    nodes_l, pods_l, gangs, quotas = generators.quota_colocation(
        seed=seed, pods=pods, nodes=nodes, tenants=2
    )
    req, _ = build_sync_request(nodes_l, pods_l, gangs, quotas)
    return req, nodes_l


def _warm_usage_frame(prev, bump):
    cur = prev.copy()
    cur.flat[bump % cur.size] += 1 + bump
    warm = pb2.SyncRequest()
    warm.nodes.usage.CopyFrom(numpy_to_tensor(cur, prev))
    return warm, cur


NASTY = FaultPlan(drop=0.12, duplicate=0.12, reorder=0.18,
                  corrupt=0.08, truncate=0.06)


class TestChaosAcceptance:
    def test_leader_kill_warm_restart_then_promotion(self, tmp_path):
        """THE acceptance run: ~36 warm/scalar Syncs through drop/
        corrupt/truncate/reorder channels; the leader is killed twice
        mid-storm — recovered once by journal warm-restart (same
        chain, no follower full resync beyond what the faults caused)
        and once by promoting a follower (epoch fence) — and the tier
        ends byte-identical to the unfaulted oracle.  The torn-
        snapshot invariant is asserted on EVERY delivery inside
        ChaosTier; this test also pins the fault mix actually fired."""
        req, nodes_l = _tiny_sync()
        tier = ChaosTier(
            str(tmp_path), followers=2, plan=NASTY, seed=11
        )
        tier.sync(req)
        prev = np.asarray(
            [res.resource_vector(n.get("usage", {})) for n in nodes_l],
            dtype=np.int64,
        )
        rng = np.random.default_rng(5)
        pre_kill_sid = None
        for step in range(36):
            if step == 12:
                pre_kill_sid = tier.leader.snapshot_id()
                tier.crash_leader()
                stats = tier.restart_leader()
                # journal warm-restart: the SAME s<epoch>-<gen> chain
                assert stats["truncated"] is None
                assert stats["resumed_id"] == pre_kill_sid
                assert tier.leader.snapshot_id() == pre_kill_sid
                assert stats["replay_ms"] is not None
                continue
            if step == 24:
                tier.crash_leader()
                old_epoch = pre_kill_sid.split("-")[0]
                sid = tier.promote(0)
                # promotion bumps the epoch (the fence), keeps serving
                assert not sid.startswith(old_epoch)
                continue
            if step % 7 == 3:
                scalar = pb2.SyncRequest()
                P = tier.leader.state.pod_requests.shape[0]
                scalar.pods.priority.extend(
                    int(v) for v in rng.integers(0, 9000, P)
                )
                tier.sync(scalar)
            else:
                warm, prev = _warm_usage_frame(
                    prev, int(rng.integers(0, 64))
                )
                tier.sync(warm)
            tier.converge()
        tier.converge()
        # the faults actually fired — this was a chaos run, not a
        # happy path that would pass vacuously
        fired = {}
        for f in tier.followers:
            for k, v in f.channel.injected.items():
                fired[k] = fired.get(k, 0) + v
        assert fired.get("drop", 0) > 0
        assert fired.get("corrupt", 0) + fired.get("truncate", 0) > 0
        assert fired.get("reorder", 0) > 0
        assert tier.resyncs > 0  # the documented recovery path ran
        assert tier.torn_checks > 30

    def test_warm_path_retrace_free_after_recovery(self, tmp_path):
        """After a crash + journal warm-restart, the leader's warm
        delta/Score stream must hold ZERO jit cache misses — recovery
        replays through the same stage/commit seam, so the compiled
        warm path survives the restart's state rebuild."""
        from koordinator_tpu.analysis import retrace_guard

        req, nodes_l = _tiny_sync()
        tier = ChaosTier(str(tmp_path), followers=1, seed=2)
        tier.sync(req)
        prev = np.asarray(
            [res.resource_vector(n.get("usage", {})) for n in nodes_l],
            dtype=np.int64,
        )
        for i in range(2):
            warm, prev = _warm_usage_frame(prev, i)
            tier.sync(warm)
        tier.crash_leader()
        stats = tier.restart_leader()
        assert stats["resumed_id"] is not None

        def warm_step(i):
            nonlocal prev
            warm, prev = _warm_usage_frame(prev, i)
            tier.sync(warm)
            sid = tier.leader.snapshot_id()
            tier.leader.score(pb2.ScoreRequest(
                snapshot_id=sid, top_k=4, flat=True
            ))

        # one warm-up rep compiles against the replayed snapshot; the
        # guarded stream must then be retrace-free
        warm_step(100)
        with retrace_guard(budget=0) as counter:
            for i in range(101, 104):
                warm_step(i)
        assert counter.traces == 0 and counter.compiles == 0
        tier.converge()

    def test_stalled_follower_catches_up_without_double_apply(
        self, tmp_path
    ):
        """A stalled follower buffers the live stream; on unstall the
        late frames apply IN ORDER (duplicates drop as stale) and the
        follower converges — never a double apply, never a tear."""
        req, nodes_l = _tiny_sync()
        tier = ChaosTier(
            str(tmp_path), followers=2,
            plan=FaultPlan(duplicate=0.3), seed=7,
        )
        tier.sync(req)
        tier.stall_follower(1)
        prev = np.asarray(
            [res.resource_vector(n.get("usage", {})) for n in nodes_l],
            dtype=np.int64,
        )
        for i in range(6):
            warm, prev = _warm_usage_frame(prev, i)
            tier.sync(warm)
        stalled_sid = tier.followers[1].servicer.snapshot_id()
        assert stalled_sid != tier.leader.snapshot_id()
        # reads on the stalled follower still serve (stale, consistent)
        assert flat_score_bytes(tier.followers[1].servicer, stalled_sid)
        tier.unstall_follower(1)
        tier.converge()

    def test_injected_launch_failure_routes_to_caller_only(
        self, tmp_path
    ):
        """A device launch failing mid-batch errors THAT caller and
        leaves the daemon serving: the next Score succeeds against
        unchanged state."""
        req, _ = _tiny_sync()
        tier = ChaosTier(str(tmp_path), followers=0, seed=1)
        sid = tier.sync(req)
        want = flat_score_bytes(tier.leader, sid)
        with fail_next_launch(tier.leader):
            with pytest.raises(RuntimeError, match="chaos"):
                tier.leader.score(pb2.ScoreRequest(
                    snapshot_id=sid, top_k=8, flat=True
                ))
        assert flat_score_bytes(tier.leader, sid) == want
        tier.converge()

    def test_journal_tail_damage_mid_tier_fences_not_forks(
        self, tmp_path
    ):
        """Tear the journal tail while a follower already holds the
        torn frames, then warm-restart: the leader rebases onto a
        fresh epoch (the fenced resync) and the follower converges to
        it — the rewound generation numbers are never re-minted on the
        old chain (the fork the epoch fence alone cannot see)."""
        req, nodes_l = _tiny_sync()
        tier = ChaosTier(str(tmp_path), followers=1, seed=4)
        tier.sync(req)
        prev = np.asarray(
            [res.resource_vector(n.get("usage", {})) for n in nodes_l],
            dtype=np.int64,
        )
        for i in range(3):
            warm, prev = _warm_usage_frame(prev, i)
            tier.sync(warm)
        tier.converge()
        old_sid = tier.leader.snapshot_id()
        tier.crash_leader()
        tier.damage_journal(cut_bytes=9)
        stats = tier.restart_leader()
        assert stats["truncated"] is not None
        new_sid = tier.leader.snapshot_id()
        assert new_sid != old_sid
        assert new_sid.split("-")[0] != old_sid.split("-")[0]
        # the tier reconverges on the new chain; oracle parity is
        # deliberately NOT asserted here — the torn frame's Sync is
        # gone from the journal, so the leader serves the last DURABLE
        # state (that is the contract: recovered, consistent, fenced)
        for f in tier.followers:
            assert f.servicer.snapshot_id() == new_sid

    def test_admission_still_sheds_during_recovery(self, tmp_path):
        """Crash tolerance composes with admission control: a gated,
        journal-recovered daemon still sheds past --max-inflight."""
        req, _ = _tiny_sync()
        tier = ChaosTier(
            str(tmp_path), followers=0, seed=3,
            servicer_kw={"score_memo": False, "max_inflight": 1},
        )
        sid = tier.sync(req)
        tier.crash_leader()
        tier.restart_leader()
        sid = tier.leader.snapshot_id()
        held = tier.leader.admission.admit("score")
        held.__enter__()
        try:
            with pytest.raises(ResourceExhausted):
                tier.leader.score(pb2.ScoreRequest(
                    snapshot_id=sid, top_k=4, flat=True
                ))
        finally:
            held.__exit__(None, None, None)
        assert flat_score_bytes(tier.leader, sid)
