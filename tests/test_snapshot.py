import numpy as np

from koordinator_tpu.model import resources as res
from koordinator_tpu.model.snapshot import (
    DEFAULT_MEMORY_REQUEST,
    DEFAULT_MILLI_CPU_REQUEST,
    PriorityClass,
    QoSClass,
    encode_snapshot,
    estimate_pod,
)

Mi = 1024 * 1024
_CPU = res.RESOURCE_INDEX[res.CPU]
_MEM = res.RESOURCE_INDEX[res.MEMORY]
_BCPU = res.RESOURCE_INDEX[res.BATCH_CPU]
_BMEM = res.RESOURCE_INDEX[res.BATCH_MEMORY]


def _vec(**kw):
    v = [0] * res.NUM_RESOURCES
    for name, val in kw.items():
        v[res.RESOURCE_INDEX[{"cpu": res.CPU, "mem": res.MEMORY, "bcpu": res.BATCH_CPU, "bmem": res.BATCH_MEMORY}[name]]] = val
    return v


class TestEstimator:
    """Parity with default_estimator.go estimatedUsedByResource."""

    def test_request_scaled(self):
        # 1000m * 85% = 850, 1000Mi * 70% = 700Mi
        est = estimate_pod(_vec(cpu=1000, mem=1000 * Mi), _vec(), PriorityClass.PROD)
        assert est[_CPU] == 850
        assert est[_MEM] == 700 * Mi

    def test_limit_greater_uses_100pct(self):
        est = estimate_pod(_vec(cpu=1000), _vec(cpu=2000), PriorityClass.PROD)
        assert est[_CPU] == 2000

    def test_zero_defaults(self):
        est = estimate_pod(_vec(), _vec(), PriorityClass.PROD)
        assert est[_CPU] == DEFAULT_MILLI_CPU_REQUEST
        assert est[_MEM] == DEFAULT_MEMORY_REQUEST

    def test_batch_translation(self):
        # batch pod: estimator reads batch-cpu/batch-memory slots
        est = estimate_pod(_vec(bcpu=4000, bmem=2048 * Mi), _vec(), PriorityClass.BATCH)
        assert est[_CPU] == round(4000 * 0.85)
        assert est[_MEM] == round(2048 * Mi * 0.70)

    def test_rounding_half_away(self):
        # 3m * 85% = 2.55 -> 3 (Go math.Round)
        est = estimate_pod(_vec(cpu=3), _vec(), PriorityClass.PROD)
        assert est[_CPU] == 3


class TestEncode:
    def test_padding_and_masks(self):
        nodes = [{"name": "a", "allocatable": {"cpu": "4", "memory": "8Gi"}}]
        pods = [
            {"name": "p1", "requests": {"cpu": "1"}, "priority": 9100, "qos": "LS"},
            {"name": "p2", "requests": {"cpu": "2"}, "priority_class": "koord-batch", "qos": "BE"},
        ]
        snap = encode_snapshot(nodes, pods)
        assert snap.nodes.valid.shape[0] == 8  # min bucket
        assert snap.num_nodes == 1
        assert snap.num_pods == 2
        assert int(snap.pods.priority_class[0]) == PriorityClass.PROD
        assert int(snap.pods.priority_class[1]) == PriorityClass.BATCH
        assert int(snap.pods.qos[1]) == QoSClass.BE
        assert not bool(snap.pods.valid[2])
        np.testing.assert_array_equal(
            np.asarray(snap.pods.requests[0])[_CPU], 1000
        )

    def test_gang_quota_wiring(self):
        nodes = [{"name": "a", "allocatable": {"cpu": "4"}}]
        gangs = [{"name": "g0", "min_member": 3}]
        quotas = [{"name": "q0", "runtime": {"cpu": "10"}, "used": {"cpu": "1"}}]
        pods = [
            {"name": "p", "requests": {"cpu": "1"}, "gang": "g0", "quota": "q0"},
            {"name": "p2", "requests": {"cpu": "1"}},
        ]
        snap = encode_snapshot(nodes, pods, gangs, quotas)
        assert int(snap.pods.gang_id[0]) == 0
        assert int(snap.pods.quota_id[0]) == 0
        assert int(snap.pods.gang_id[1]) == -1
        assert int(snap.gangs.min_member[0]) == 3
        assert int(snap.quotas.runtime[0][_CPU]) == 10000
