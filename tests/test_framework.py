"""FrameworkExtender pipeline: transformers, plugin composition, Reserve
hooks, debug tables, PreBind patch merging.

Reference seams under test: pkg/scheduler/frameworkext
(framework_extender.go transformer interposition + debugScores,
errorhandler_dispatcher.go, plugins/defaultprebind).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from koordinator_tpu.config import CycleConfig
from koordinator_tpu.model import resources as res
from koordinator_tpu.model.device import encode_devices
from koordinator_tpu.model.snapshot import encode_snapshot
from koordinator_tpu.model.topology import CPUTopology, encode_zones
from koordinator_tpu.ops.numa import POLICY_SINGLE_NUMA_NODE
from koordinator_tpu.scheduler.framework import (
    CycleContext,
    FrameworkExtender,
    TensorPlugin,
)
from koordinator_tpu.scheduler.plugins import (
    DeviceSharePlugin,
    NodeNUMAResourcePlugin,
    ReservationPlugin,
)
from koordinator_tpu.solver.greedy import STATUS_ASSIGNED


def _snapshot(n_nodes=2, n_pods=3, cpu="2", node_cpu="16"):
    nodes = [
        {
            "name": f"node-{i}",
            "allocatable": {"cpu": node_cpu, "memory": "32Gi", "pods": "110"},
            "usage": {"cpu": "1", "memory": "2Gi"},
        }
        for i in range(n_nodes)
    ]
    pods = [
        {
            "name": f"pod-{i}",
            "requests": {"cpu": cpu, "memory": "4Gi"},
            "qos": "LSR",
            "priority": 9000 + i,
        }
        for i in range(n_pods)
    ]
    return encode_snapshot(nodes, pods)


class TestPipeline:
    def test_plain_cycle_assigns(self):
        fx = FrameworkExtender()
        ctx = CycleContext(snapshot=_snapshot())
        result = fx.run_cycle(ctx)
        a = np.asarray(result.assignment)
        assert (a[:3] >= 0).all()

    def test_transformer_interposition(self):
        calls = []

        def t(ctx):
            calls.append("before_pre_filter")
            return ctx

        fx = FrameworkExtender(before_pre_filter=[t])
        fx.run_cycle(CycleContext(snapshot=_snapshot()))
        assert calls == ["before_pre_filter"]

    def test_plugin_mask_excludes_node(self):
        class VetoNode0(TensorPlugin):
            name = "veto"

            def filter_mask(self, ctx):
                P = ctx.snapshot.pods.capacity
                N = ctx.snapshot.nodes.capacity
                m = jnp.ones((P, N), bool)
                return m.at[:, 0].set(False)

        fx = FrameworkExtender([VetoNode0()])
        result = fx.run_cycle(CycleContext(snapshot=_snapshot()))
        a = np.asarray(result.assignment)
        assert (a[:3] != 0).all() and (a[:3] >= 0).all()

    def test_plugin_score_steers_choice(self):
        class PreferNode1(TensorPlugin):
            name = "prefer1"
            weight = 100

            def score(self, ctx):
                P = ctx.snapshot.pods.capacity
                N = ctx.snapshot.nodes.capacity
                s = jnp.zeros((P, N), jnp.int64)
                return s.at[:, 1].set(100)

        fx = FrameworkExtender([PreferNode1()])
        result = fx.run_cycle(CycleContext(snapshot=_snapshot()))
        a = np.asarray(result.assignment)
        assert (a[:3] == 1).all()

    def test_debug_scores_table(self):
        class Scorer(TensorPlugin):
            name = "scorer"

            def score(self, ctx):
                P = ctx.snapshot.pods.capacity
                N = ctx.snapshot.nodes.capacity
                return jnp.ones((P, N), jnp.int64) * 7

        fx = FrameworkExtender([Scorer()], debug_top_n=2)
        fx.run_cycle(CycleContext(snapshot=_snapshot()))
        assert fx.last_debug is not None
        assert "scorer" in str(fx.last_debug)

    def test_error_handler_dispatch(self):
        class FailingReserve(TensorPlugin):
            name = "fails"

            def reserve(self, ctx, pod_idx, node_idx):
                raise RuntimeError("boom")

        handled = []
        fx = FrameworkExtender([FailingReserve()])
        fx.register_error_handler(lambda ctx, p, exc: handled.append(p) or True)
        fx.run_cycle(CycleContext(snapshot=_snapshot()))
        assert handled  # dispatcher claimed the failure, no raise

    def test_error_unhandled_raises(self):
        class FailingReserve(TensorPlugin):
            name = "fails"

            def reserve(self, ctx, pod_idx, node_idx):
                raise RuntimeError("boom")

        fx = FrameworkExtender([FailingReserve()])
        with pytest.raises(RuntimeError):
            fx.run_cycle(CycleContext(snapshot=_snapshot()))


class TestNUMAPluginIntegration:
    def test_single_numa_policy_filters_and_cpuset_reserved(self):
        snap = _snapshot(n_nodes=2, n_pods=1, cpu="4")
        zones = encode_zones(
            [
                # node-0: two 8c zones -> 4c pod fits one zone
                {"zones": [{"allocatable": {"cpu": "8", "memory": "16Gi"}}] * 2},
                # node-1: two zones with tiny free cpu -> single-numa fails
                {
                    "zones": [
                        {
                            "allocatable": {"cpu": "8", "memory": "16Gi"},
                            "requested": {"cpu": "6"},
                        }
                    ]
                    * 2
                },
            ],
            node_bucket=snap.nodes.capacity,
        )
        policy = jnp.full((snap.nodes.capacity,), POLICY_SINGLE_NUMA_NODE, jnp.int32)
        topo = CPUTopology.build(1, 2, 4, 2)
        fx = FrameworkExtender([NodeNUMAResourcePlugin()])
        ctx = CycleContext(
            snapshot=snap,
            extras={
                "zones": zones,
                "numa_policy": policy,
                "cpu_topologies": {0: topo},
            },
        )
        result = fx.run_cycle(ctx)
        a = np.asarray(result.assignment)
        assert a[0] == 0  # node-1 rejected by single-numa admission
        cpus = ctx.state["cpuset_allocations"][0]
        assert len(cpus) == 4
        # FullPCPUs on one NUMA node
        assert {topo.details[c].node for c in cpus} == {0} or {
            topo.details[c].node for c in cpus
        } == {1}
        patches = fx.pre_bind_patches(ctx, result)
        assert "resource-status" in str(patches[0])


class TestDevicePluginIntegration:
    def test_device_fit_and_reserve(self):
        # device totals also land in node allocatable (koord-manager's
        # device resource calculator writes gpu-core etc. onto the Node)
        snap = encode_snapshot(
            [
                {
                    "name": "node-0",
                    "allocatable": {
                        "cpu": "16",
                        "memory": "32Gi",
                        res.GPU_CORE: 100,
                        res.GPU_MEMORY: "16Gi",
                        res.GPU_MEMORY_RATIO: 100,
                    },
                },
                {"name": "node-1", "allocatable": {"cpu": "16", "memory": "32Gi"}},
            ],
            [
                {
                    "name": "gpu-pod",
                    "requests": {
                        "cpu": "2",
                        "memory": "4Gi",
                        res.GPU_CORE: 100,
                        res.GPU_MEMORY_RATIO: 100,
                    },
                }
            ],
        )
        devices = encode_devices(
            [
                {
                    "devices": [
                        {
                            "type": "gpu",
                            "minor": 0,
                            "total": {
                                res.GPU_CORE: 100,
                                res.GPU_MEMORY: "16Gi",
                                res.GPU_MEMORY_RATIO: 100,
                            },
                        }
                    ]
                },
                {"devices": []},
            ],
            node_bucket=snap.nodes.capacity,
        )
        minors = {
            0: [
                {
                    "minor": 0,
                    "total": {
                        res.GPU_CORE: 100,
                        res.GPU_MEMORY: 16 * 1024**3,
                        res.GPU_MEMORY_RATIO: 100,
                    },
                }
            ]
        }
        fx = FrameworkExtender([DeviceSharePlugin()])
        ctx = CycleContext(
            snapshot=snap, extras={"devices": devices, "device_minors": minors}
        )
        result = fx.run_cycle(ctx)
        a = np.asarray(result.assignment)
        assert a[0] == 0  # only node-0 has the GPU
        alloc = ctx.state["device_allocations"][0]
        assert [e["minor"] for e in alloc["gpu"]] == [0]
        # free deducted on the minor
        assert minors[0][0]["free"][res.GPU_CORE] == 0


class TestReservationPluginIntegration:
    def test_reservation_steers_to_reserved_node(self):
        from koordinator_tpu.model.reservation import encode_reservations

        snap = _snapshot(n_nodes=2, n_pods=1)
        pods = [{"name": "pod-0", "labels": {"app": "web"}}]
        rsv = encode_reservations(
            [
                {
                    "name": "rsv",
                    "node": "node-1",
                    "allocatable": {"cpu": "4", "memory": "8Gi"},
                    "owners": [{"label_selector": {"app": "web"}}],
                    "order": 1,
                }
            ],
            pods,
            ["node-0", "node-1"],
            pod_bucket=snap.pods.capacity,
        )
        fx = FrameworkExtender([ReservationPlugin()])
        ctx = CycleContext(snapshot=snap, extras={"reservations": rsv})
        result = fx.run_cycle(ctx)
        a = np.asarray(result.assignment)
        assert a[0] == 1
        patches = fx.pre_bind_patches(ctx, result)
        assert "reservation-allocated" in str(patches.get(0, {}))
