"""ISSUE 9: the incremental score engine.

The resident [P, N] score/feasible tensors are first-class leaves of
the device-resident state: warm delta Syncs accumulate dirty
column/row sets through the stage/commit seam (bridge/state.py
``ScoreResidency``) and the next Score advances the tensors by
rescoring ONLY what the deltas invalidated (solver/incremental.py),
bit-identical to a full rescore by construction.

Covered here:

* parity fuzz — randomized warm streams (deltas touching 1..N nodes
  and pods, scalar churn, resizes, full-resync events, interleaved
  Scores at mixed top_k) produce Score replies byte-identical between
  the incremental engine and the full-rescore oracle, on mesh sizes
  {1, 8};
* the ScoreMemo x incremental seam — a memo entry keyed on the
  pre-delta snapshot id never serves after the bump, the incremental
  launch's readback populates the memo for the NEW id, and an
  owner-failure on the incremental launch falls back to a full rescore
  instead of poisoning the resident tensor;
* dirty-set mechanics — accumulation across multiple Syncs between
  Scores, the zero-dirty short-circuit (quota-only deltas: score_cycle
  reads no quota state), the ``--score-incr-max-ratio`` fallback, the
  CycleConfig-change drop, and the ``score_incr=False`` opt-out;
* the replication follower (ISSUE 8 tier) maintaining ITS resident
  score tensors incrementally from streamed delta frames, byte-parity
  with the leader after every applied frame.
"""

import dataclasses

import numpy as np
import pytest

import jax

from koordinator_tpu.bridge.codegen import pb2
from koordinator_tpu.bridge.server import ScorerServicer
from koordinator_tpu.bridge.state import numpy_to_tensor
from koordinator_tpu.config import CycleConfig
from koordinator_tpu.parallel import cluster_mesh

from test_resident_warm import (
    R,
    _full_sync_request,
    _mutate,
    _random_state,
)


def _flat(sv, k=8):
    """One flat Score's payload bytes (build_ms excluded: it is the
    one timing field and the parity claim is over the data)."""
    reply = sv.score(pb2.ScoreRequest(
        snapshot_id=sv.snapshot_id(), top_k=k, flat=True
    ))
    return reply.flat.SerializeToString()


def _legacy(sv, k=8):
    reply = sv.score(pb2.ScoreRequest(
        snapshot_id=sv.snapshot_id(), top_k=k, flat=False
    ))
    clone = pb2.ScoreReply()
    clone.CopyFrom(reply)
    clone.build_ms = 0.0
    return clone.SerializeToString()


def _incr_count(sv, result):
    return sv.telemetry.registry.get(
        "koord_scorer_score_incr_total", {"result": result}
    ) or 0


def _quota_delta(state, rng):
    """A quota-only sparse delta: dirties ZERO score rows by design
    (score_cycle reads no quota state)."""
    prev = state["quota_used"].copy()
    flat = state["quota_used"].reshape(-1)
    flat[int(rng.randint(flat.size))] += 1
    req = pb2.SyncRequest()
    req.quotas.used.CopyFrom(numpy_to_tensor(state["quota_used"], prev))
    return req


class TestParityFuzz:
    @pytest.mark.parametrize("seed", range(4))
    def test_incremental_matches_full_rescore_oracle(self, seed):
        """The acceptance fuzz: a randomized warm stream through TWO
        servicers — incremental engine vs score_incr=False oracle —
        with Scores at mixed top_k after every frame, byte-identical
        replies throughout, including across resizes (residency drop)
        and a mid-stream full resync."""
        rng = np.random.RandomState(1000 + seed)
        state = _random_state(
            rng, n_nodes=int(rng.randint(4, 10)),
            n_pods=int(rng.randint(8, 24)),
            with_quota=bool(seed % 2),
        )
        # memo stays ON for half the seeds: memo-served batches must be
        # byte-identical too (they slice the incremental launch's
        # readback), and the seam is exactly what ISSUE 9 touches
        memo = bool(seed % 2)
        # ratio gate OPEN (1.0): tiny fuzz geometries put most deltas
        # past the default 0.25 cost gate, and this test exists to
        # exercise the KERNEL maximally — the gate has its own test
        incr = ScorerServicer(score_memo=memo, score_incr_max_ratio=1.0)
        full = ScorerServicer(score_memo=memo, score_incr=False)
        for sv in (incr, full):
            sv.sync(_full_sync_request(state))
        assert _flat(incr, 4) == _flat(full, 4)
        for cycle in range(12):
            if cycle == 6:
                # full-resync event: the whole state rides one cold
                # frame; the resident score tensors must drop, not
                # serve stale columns
                req = _full_sync_request(state)
            else:
                req, _resized = _mutate(rng, state)
            for sv in (incr, full):
                sv.sync(req)
            assert incr.state.last_sync_path == full.state.last_sync_path
            k = int(rng.choice([1, 3, 8, 17]))
            assert _flat(incr, k) == _flat(full, k), (
                f"seed={seed} cycle={cycle} k={k}"
            )
            if rng.rand() < 0.3:
                assert _legacy(incr, 5) == _legacy(full, 5), (
                    f"seed={seed} cycle={cycle} legacy"
                )
        # the stream must actually have exercised the engine
        assert _incr_count(incr, "incr") > 0, "fuzz never ran incrementally"
        assert _incr_count(full, "incr") == 0  # the oracle never does

    @pytest.mark.parametrize("seed", (0, 1))
    def test_mesh_sharded_incremental_matches_oracle(self, seed):
        """Same fuzz over the 8-device mesh-resident snapshot: the
        dirty-column rescore is shard-local (solver/incremental.py
        ``_rescore_sharded``) and must stay byte-identical to the
        full-rescore oracle on the SAME mesh — and to the single-chip
        incremental servicer."""
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 (virtual) devices")
        rng = np.random.RandomState(2000 + seed)
        state = _random_state(rng, n_nodes=6, n_pods=16,
                              with_quota=bool(seed))
        mesh = cluster_mesh(jax.devices())
        incr = ScorerServicer(
            mesh=mesh, mesh_resident=True, score_memo=False,
            score_incr_max_ratio=1.0,
        )
        full = ScorerServicer(
            mesh=mesh, mesh_resident=True, score_memo=False,
            score_incr=False,
        )
        chip = ScorerServicer(score_memo=False)
        for sv in (incr, full, chip):
            sv.sync(_full_sync_request(state))
        for cycle in range(8):
            req, _resized = _mutate(rng, state)
            for sv in (incr, full, chip):
                sv.sync(req)
            k = int(rng.choice([2, 8, 13]))
            a, b, c = _flat(incr, k), _flat(full, k), _flat(chip, k)
            assert a == b, f"seed={seed} cycle={cycle} mesh incr vs full"
            assert a == c, f"seed={seed} cycle={cycle} mesh vs single-chip"
        assert _incr_count(incr, "incr") > 0


class TestDirtyMechanics:
    def _servicer(self, **kw):
        rng = np.random.RandomState(7)
        state = _random_state(rng, n_nodes=6, n_pods=12, with_quota=True)
        sv = ScorerServicer(score_memo=False, **kw)
        sv.sync(_full_sync_request(state))
        return sv, state, rng

    def _node_delta(self, state, row=0, col=1, bump=7):
        prev = state["node_usage"].copy()
        state["node_usage"][row, col] += bump
        req = pb2.SyncRequest()
        req.nodes.usage.CopyFrom(numpy_to_tensor(state["node_usage"], prev))
        return req

    def test_dirty_sets_accumulate_across_syncs(self):
        """Several warm commits between Scores union their dirt; the
        one Score that follows incorporates all of it and clears."""
        sv, state, _rng = self._servicer()
        _flat(sv)  # populates the residency (result=full)
        sv.sync(self._node_delta(state, row=1))
        sv.sync(self._node_delta(state, row=3))
        sv.sync(self._node_delta(state, row=1, col=2))
        res = sv.state.score_residency()
        assert res is not None and res.dirty_nodes == {1, 3}
        oracle = ScorerServicer(score_memo=False, score_incr=False)
        oracle.sync(_full_sync_request(state))
        assert _flat(sv) == _flat(oracle)
        assert sv.state.score_residency().dirty_nodes == set()
        assert _incr_count(sv, "incr") == 1

    def test_quota_only_delta_rescores_zero_columns(self):
        """score_cycle reads no quota state: a quota-only delta stream
        advances the generation with ZERO dirty rows — the next Score
        is an incremental launch that touches no kernel at all, and the
        resident tensors are served as-is (still memo-missed: the
        snapshot id moved)."""
        sv, state, rng = self._servicer()
        _flat(sv)
        scores_before = sv.state.score_residency().scores
        sv.sync(_quota_delta(state, rng))
        assert sv.state.last_sync_path == "warm"
        res = sv.state.score_residency()
        assert res is not None
        assert res.dirty_nodes == set() and res.dirty_pods == set()
        oracle = ScorerServicer(score_memo=False, score_incr=False)
        oracle.sync(_full_sync_request(state))
        assert _flat(sv) == _flat(oracle)
        # the zero-dirty path reuses the very tensors — no new buffers
        assert sv.state.score_residency().scores is scores_before
        assert _incr_count(sv, "incr") == 1

    def test_node_fresh_flip_dirties_exactly_the_flipped_columns(self):
        sv, state, _rng = self._servicer()
        _flat(sv)
        flipped = state["node_fresh"].copy()
        flipped[2] = not flipped[2]
        flipped[4] = not flipped[4]
        state["node_fresh"] = flipped
        req = pb2.SyncRequest()
        req.nodes.metric_fresh.extend(bool(b) for b in flipped)
        sv.sync(req)
        assert sv.state.last_sync_path == "warm"
        assert sv.state.score_residency().dirty_nodes == {2, 4}
        oracle = ScorerServicer(score_memo=False, score_incr=False)
        oracle.sync(_full_sync_request(state))
        assert _flat(sv) == _flat(oracle)

    def test_priority_churn_dirties_pods_whose_class_moved(self):
        """Raw priority feeds scoring only through the effective
        priority CLASS: a priority change inside the same band dirties
        nothing, a band crossing dirties that pod's row."""
        sv, state, _rng = self._servicer()
        _flat(sv)
        prio = state["pod_priority"].copy()
        prio[0] = 9500   # -> PROD band
        prio[1] = prio[1] + 1 if prio[1] % 1000 < 900 else prio[1] - 1
        state["pod_priority"] = prio
        req = pb2.SyncRequest()
        req.pods.priority.extend(int(v) for v in prio)
        sv.sync(req)
        res = sv.state.score_residency()
        assert res is not None
        assert 0 in res.dirty_pods or res.dirty_pods == set()
        oracle = ScorerServicer(score_memo=False, score_incr=False)
        oracle.sync(_full_sync_request(state))
        assert _flat(sv) == _flat(oracle)

    def test_ratio_gate_falls_back_to_full(self):
        sv, state, _rng = self._servicer(score_incr_max_ratio=0.0)
        _flat(sv)
        sv.sync(self._node_delta(state))
        oracle = ScorerServicer(score_memo=False, score_incr=False)
        oracle.sync(_full_sync_request(state))
        assert _flat(sv) == _flat(oracle)
        assert _incr_count(sv, "fallback") == 1
        assert _incr_count(sv, "incr") == 0
        # the full launch REFRESHED the residency: dirt cleared
        assert sv.state.score_residency().dirty_nodes == set()

    def test_full_tensor_reupload_drops_attribution(self):
        """A warm frame carrying a FULL tensor (row attribution lost)
        must drop the residency — the next Score full-rescores."""
        sv, state, _rng = self._servicer()
        _flat(sv)
        state["node_usage"] = state["node_usage"] + 1  # every cell moves
        req = pb2.SyncRequest()
        req.nodes.usage.CopyFrom(numpy_to_tensor(state["node_usage"]))
        assert req.nodes.usage.data  # rode full, not delta
        sv.sync(req)
        assert sv.state.last_sync_path == "warm"
        assert sv.state.score_residency() is None
        oracle = ScorerServicer(score_memo=False, score_incr=False)
        oracle.sync(_full_sync_request(state))
        assert _flat(sv) == _flat(oracle)
        assert _incr_count(sv, "full") == 2  # cold populate + re-populate

    def test_cycle_config_change_drops_residency(self):
        sv, state, _rng = self._servicer()
        _flat(sv)
        sv.sync(self._node_delta(state))
        sv.cfg = CycleConfig(wave=8, top_m=2)  # operator reconfig
        oracle = ScorerServicer(
            CycleConfig(wave=8, top_m=2), score_memo=False,
            score_incr=False,
        )
        oracle.sync(_full_sync_request(state))
        assert _flat(sv) == _flat(oracle)
        # the stale-config tensors were dropped, then re-populated
        assert _incr_count(sv, "incr") == 0
        assert _incr_count(sv, "full") == 2
        assert sv.state.score_residency().cfg == sv.cfg

    def test_opt_out_never_keeps_residency(self):
        sv, state, _rng = self._servicer(score_incr=False)
        _flat(sv)
        assert sv.state.score_residency() is None
        sv.sync(self._node_delta(state))
        _flat(sv)
        assert _incr_count(sv, "incr") == 0
        assert _incr_count(sv, "full") == 0  # engine fully out of play


class TestScoreMemoSeam:
    def _pair(self):
        rng = np.random.RandomState(11)
        state = _random_state(rng, n_nodes=6, n_pods=12, with_quota=False)
        sv = ScorerServicer()  # memo AND incremental engine on
        sv.sync(_full_sync_request(state))
        oracle = ScorerServicer(score_memo=False, score_incr=False)
        oracle.sync(_full_sync_request(state))
        return sv, oracle, state

    def _memo_hits(self, sv):
        return sv.telemetry.registry.get(
            "koord_scorer_score_memo_total", {"result": "hit"}
        ) or 0

    def _sync_both(self, sv, oracle, state):
        prev = state["node_usage"].copy()
        state["node_usage"][0, 1] += 3
        req = pb2.SyncRequest()
        req.nodes.usage.CopyFrom(numpy_to_tensor(state["node_usage"], prev))
        sv.sync(req)
        oracle.sync(pb2.SyncRequest.FromString(req.SerializeToString()))

    def test_pre_delta_memo_never_serves_after_bump(self):
        """The memo entry certifies the PRE-delta snapshot id; after
        the bump the Score must miss it and launch (incrementally) —
        serving the stale entry would hand out pre-delta scores under
        a post-delta id."""
        sv, oracle, state = self._pair()
        _flat(sv, 4)           # launch, populates memo for sid1
        assert _flat(sv, 4) == _flat(oracle, 4)  # memo-served, identical
        hits_before = self._memo_hits(sv)
        assert hits_before >= 1
        self._sync_both(sv, oracle, state)
        assert _flat(sv, 4) == _flat(oracle, 4)  # post-delta values
        assert self._memo_hits(sv) == hits_before  # no stale hit
        assert _incr_count(sv, "incr") == 1

    def test_incremental_launch_populates_memo_for_new_id(self):
        """The incremental launch's readback must publish the memo
        under the NEW snapshot id: a follow-up identical Score is
        memo-served, no second launch."""
        sv, oracle, state = self._pair()
        _flat(sv, 4)
        self._sync_both(sv, oracle, state)
        assert _flat(sv, 4) == _flat(oracle, 4)  # incremental launch
        hits = self._memo_hits(sv)
        incr_launches = _incr_count(sv, "incr")
        assert _flat(sv, 4) == _flat(oracle, 4)  # identical follow-up
        assert self._memo_hits(sv) == hits + 1
        assert _incr_count(sv, "incr") == incr_launches  # no new launch

    def test_owner_failure_falls_back_without_poisoning(self, monkeypatch):
        """An incremental launch that raises must (a) still answer its
        batch exactly via the full rescore, (b) count result=fallback,
        and (c) leave a residency the NEXT launch can trust — never a
        half-scattered tensor."""
        from koordinator_tpu.solver import incremental as incr_mod

        sv, oracle, state = self._pair()
        _flat(sv, 4)
        self._sync_both(sv, oracle, state)
        real = incr_mod.rescore_dirty
        calls = []

        def boom(*a, **kw):
            calls.append(1)
            raise RuntimeError("injected owner failure")

        monkeypatch.setattr(incr_mod, "rescore_dirty", boom)
        assert _flat(sv, 4) == _flat(oracle, 4)  # exact despite failure
        assert calls and _incr_count(sv, "fallback") == 1
        res = sv.state.score_residency()
        assert res is not None and res.dirty_nodes == set()
        # engine recovers: the refreshed tensors serve the next delta
        # incrementally and exactly
        monkeypatch.setattr(incr_mod, "rescore_dirty", real)
        self._sync_both(sv, oracle, state)
        assert _flat(sv, 4) == _flat(oracle, 4)
        assert _incr_count(sv, "incr") == 1


class TestFollowerIncremental:
    def test_follower_applies_frames_incrementally_with_byte_parity(self):
        """ISSUE 8 closure: a replication follower applies streamed
        delta frames through the same stage/commit seam, so its
        resident score tensors advance incrementally too — the
        follower must NOT pay the full rescores the leader skipped,
        and its Score replies must stay byte-identical to the
        leader's after every applied frame."""
        from koordinator_tpu.replication.follower import (
            APPLIED,
            FollowerServicer,
            ReplicaApplier,
        )
        from test_replication import _capture_frames, _full_frame

        rng = np.random.RandomState(17)
        state = _random_state(rng, n_nodes=6, n_pods=12, with_quota=False)
        leader = ScorerServicer(score_memo=False)
        frames = _capture_frames(leader)
        leader.sync(_full_sync_request(state))
        follower = FollowerServicer(score_memo=False)
        applier = ReplicaApplier(follower)
        assert applier.offer(_full_frame(leader)) == APPLIED
        # both tiers score once: the residency exists on each side
        assert _flat(leader, 6) == _flat(follower, 6)
        for step in range(5):
            prev = state["node_usage"].copy()
            state["node_usage"][step % 6, 2] += 5
            req = pb2.SyncRequest()
            req.nodes.usage.CopyFrom(
                numpy_to_tensor(state["node_usage"], prev)
            )
            leader.sync(req)
            assert applier.offer(frames[-1]) == APPLIED
            assert follower.state.last_sync_path == "warm"
            assert follower.snapshot_id() == leader.snapshot_id()
            assert _flat(follower, 6) == _flat(leader, 6), f"step={step}"
        # the follower's Scores after the first ran INCREMENTALLY
        assert _incr_count(follower, "incr") == 5
        assert _incr_count(follower, "full") == 1
        res = follower.state.score_residency()
        assert res is not None and res.dirty_nodes == set()

    def test_full_resync_frame_drops_follower_residency(self):
        from koordinator_tpu.replication.follower import (
            APPLIED,
            FollowerServicer,
            ReplicaApplier,
        )
        from test_replication import _full_frame

        rng = np.random.RandomState(19)
        state = _random_state(rng, n_nodes=5, n_pods=10, with_quota=False)
        leader = ScorerServicer(score_memo=False)
        leader.sync(_full_sync_request(state))
        follower = FollowerServicer(score_memo=False)
        applier = ReplicaApplier(follower)
        assert applier.offer(_full_frame(leader)) == APPLIED
        _flat(follower, 4)
        assert follower.state.score_residency() is not None
        # a reset frame swaps in a FRESH ResidentState: no stale score
        # tensors may survive the swap
        assert applier.offer(
            dataclasses.replace(_full_frame(leader), generation=0)
        ) in (APPLIED,)
        assert follower.state.score_residency() is None
        assert _flat(follower, 4) == _flat(leader, 4)


class TestMaskedTopK:
    """solver/topk.py: the packed-f64 serving top-k must be
    bit-identical to ``lax.top_k`` over the masked i64 tensor — values,
    indices, ordering AND ties — and the static bound it relies on must
    actually hold for score_cycle's output."""

    def test_packed_matches_integer_topk_with_ties(self):
        from jax import lax
        import jax.numpy as jnp

        from koordinator_tpu.solver import masked_top_k

        rng = np.random.RandomState(31)
        # heavy duplication forces the tie-break path; a column of
        # all-infeasible rows covers the sentinel ordering
        scores = jnp.asarray(
            rng.randint(0, 7, (40, 33)).astype(np.int64) * 50
        )
        feasible = jnp.asarray(rng.rand(40, 33) > 0.3)
        feasible = feasible.at[7, :].set(False)
        masked = jnp.where(feasible, scores, jnp.iinfo(jnp.int64).min)
        for k in (1, 5, 33):
            want_s, want_i = lax.top_k(masked, k)
            got_s, got_i = masked_top_k(scores, feasible, k=k, hi=300)
            np.testing.assert_array_equal(np.asarray(got_s),
                                          np.asarray(want_s), err_msg=f"k={k}")
            np.testing.assert_array_equal(np.asarray(got_i),
                                          np.asarray(want_i), err_msg=f"k={k}")

    def test_unpackable_bound_takes_integer_path_exactly(self):
        from jax import lax
        import jax.numpy as jnp

        from koordinator_tpu.solver import masked_top_k

        rng = np.random.RandomState(37)
        scores = jnp.asarray(
            rng.randint(-(2 ** 60), 2 ** 60, (8, 16)).astype(np.int64)
        )
        feasible = jnp.asarray(rng.rand(8, 16) > 0.5)
        masked = jnp.where(feasible, scores, jnp.iinfo(jnp.int64).min)
        want_s, want_i = lax.top_k(masked, 4)
        got_s, got_i = masked_top_k(scores, feasible, k=4, hi=2 ** 60)
        np.testing.assert_array_equal(np.asarray(got_s), np.asarray(want_s))
        np.testing.assert_array_equal(np.asarray(got_i), np.asarray(want_i))

    def test_out_of_bound_scores_fall_to_integer_branch_exactly(self):
        """The wire accepts arbitrary int64, so a hostile tensor can
        push scores past the static hi bound (a negative requested
        breaks least_requested_score's clamp) — the device-side
        in-bound check must route those launches to the integer branch
        of the same program, bit-exactly, instead of letting distinct
        i64 scores collapse onto one f32 rank."""
        from jax import lax
        import jax.numpy as jnp

        from koordinator_tpu.solver import masked_top_k

        rng = np.random.RandomState(41)
        # values far past 2^24 AND negative feasible values: both
        # violations of the fast path's rank contract
        scores = jnp.asarray(
            rng.randint(-(2 ** 40), 2 ** 40, (16, 24)).astype(np.int64)
        )
        feasible = jnp.asarray(rng.rand(16, 24) > 0.4)
        masked = jnp.where(feasible, scores, jnp.iinfo(jnp.int64).min)
        want_s, want_i = lax.top_k(masked, 6)
        got_s, got_i = masked_top_k(scores, feasible, k=6, hi=200)
        np.testing.assert_array_equal(np.asarray(got_s), np.asarray(want_s))
        np.testing.assert_array_equal(np.asarray(got_i), np.asarray(want_i))
        # out-of-bound values hidden behind infeasibility do NOT demote:
        # rank 0 covers them regardless of their wild values
        wild = jnp.where(feasible, jnp.clip(scores, 0, 200),
                         jnp.int64(2 ** 40))
        masked2 = jnp.where(feasible, wild, jnp.iinfo(jnp.int64).min)
        want_s, want_i = lax.top_k(masked2, 6)
        got_s, got_i = masked_top_k(wild, feasible, k=6, hi=200)
        np.testing.assert_array_equal(np.asarray(got_s), np.asarray(want_s))
        np.testing.assert_array_equal(np.asarray(got_i), np.asarray(want_i))

    def test_score_upper_bound_holds_on_fuzzed_snapshots(self):
        """The packed path's INVARIANT: score_cycle's combined scores
        sit in [0, score_upper_bound(cfg)] — every term clamps to
        [0, MAX_NODE_SCORE] per plugin weight.  A future scoring term
        that breaks this must widen score_upper_bound."""
        from koordinator_tpu.solver import score_cycle, score_upper_bound
        from koordinator_tpu.config import DEFAULT_CYCLE_CONFIG

        hi = score_upper_bound(DEFAULT_CYCLE_CONFIG)
        assert hi > 0
        for seed in range(3):
            rng = np.random.RandomState(4000 + seed)
            state = _random_state(rng, n_nodes=7, n_pods=18,
                                  with_quota=bool(seed % 2))
            sv = ScorerServicer(score_memo=False)
            sv.sync(_full_sync_request(state))
            scores, _ = score_cycle(sv.state.snapshot(),
                                    DEFAULT_CYCLE_CONFIG)
            s = np.asarray(scores)
            assert s.min() >= 0 and s.max() <= hi, (seed, s.min(), s.max())


class TestIncrementalRetraceBudget:
    def test_warm_delta_score_stream_is_retrace_free(self):
        """The ISSUE 9 acceptance: a warm delta-Sync -> incremental
        Score stream holds ZERO jit cache misses after one warm-up —
        the dirty sets ride bucket-padded index vectors, so varying
        dirty composition mints no new compiled shapes."""
        from koordinator_tpu.analysis import retrace_guard

        rng = np.random.RandomState(23)
        state = _random_state(rng, n_nodes=6, n_pods=12, with_quota=False)
        # ratio gate open: 3 dirty nodes of a 6-node table would trip
        # the default cost gate, and this test times the KERNEL path
        sv = ScorerServicer(score_memo=False, score_incr_max_ratio=1.0)
        sv.sync(_full_sync_request(state))

        def step(rows):
            prev = state["node_usage"].copy()
            for r in rows:
                state["node_usage"][r, 1] += 1
            req = pb2.SyncRequest()
            req.nodes.usage.CopyFrom(
                numpy_to_tensor(state["node_usage"], prev)
            )
            sv.sync(req)
            assert sv.state.last_sync_path == "warm"
            return _flat(sv, 3)

        _flat(sv, 3)       # warm-up: full score + residency
        step([0])          # warm-up: incremental kernel compile
        with retrace_guard(budget=0) as counter:
            for i in range(4):
                # 1..3 dirty nodes per delta: same pad bucket, and the
                # dirty-set union must not leak a count into any trace
                step([i % 6, (i + 1) % 6, (i + 2) % 6][: 1 + i % 3])
        assert counter.traces == 0 and counter.compiles == 0
        assert _incr_count(sv, "incr") == 5
