"""Descheduler runtime: profiles, plugin registry, and the ticking loop.

Reference: ``pkg/descheduler/descheduler.go:241,259`` (Start /
deschedulerOnce), ``framework/runtime/framework.go:121,310,330``.
End-to-end: two ticks drive LowNodeLoad -> MigrationController ->
PodEvictor through an actual eviction with the anomaly debounce engaged
(first overutilized observation does not evict; the second does).
"""

from typing import List

import pytest

from koordinator_tpu.descheduler.anomaly import BasicDetector
from koordinator_tpu.descheduler.evictions import PodEvictor
from koordinator_tpu.descheduler.lownodeload import LowNodeLoadArgs, NodePool
from koordinator_tpu.descheduler.migration import (
    MigrationController,
    MigrationControllerArgs,
)
from koordinator_tpu.descheduler.runtime import (
    Descheduler,
    DeschedulerProfile,
    PluginSet,
    Status,
)

Gi = 1024 * 1024 * 1024


def _pod(name, cpu="2000m", mem=4 * Gi, namespace="default"):
    return {
        "name": name,
        "namespace": namespace,
        "requests": {"cpu": cpu, "memory": mem},
        "usage": {"cpu": cpu, "memory": mem},
        "priority": 5000,
        # bare pods are never evictable (upstream DefaultEvictor)
        "owner_references": [{"kind": "ReplicaSet", "name": "rs-web"}],
    }


def _cluster() -> List[dict]:
    # one hot node (90% cpu) + three cold nodes
    hot_pods = [_pod(f"hot-{i}") for i in range(7)]
    nodes = [
        {
            "name": "hot",
            "allocatable": {"cpu": "16000m", "memory": 64 * Gi},
            "usage": {"cpu": "14400m", "memory": 30 * Gi},
            "pods": hot_pods,
        }
    ]
    for i in range(3):
        nodes.append(
            {
                "name": f"cold-{i}",
                "allocatable": {"cpu": "16000m", "memory": 64 * Gi},
                "usage": {"cpu": "1600m", "memory": 4 * Gi},
                "pods": [],
            }
        )
    return nodes


def _profile(consecutive=2):
    return DeschedulerProfile(
        name="koord-descheduler",
        plugins=PluginSet(balance=["LowNodeLoad"]),
        plugin_config={
            "LowNodeLoad": LowNodeLoadArgs(
                node_pools=[
                    NodePool(
                        low_thresholds={"cpu": 30, "memory": 30},
                        high_thresholds={"cpu": 70, "memory": 70},
                        consecutive_abnormalities=consecutive,
                    )
                ]
            )
        },
    )


class TestDeschedulerLoop:
    def test_two_ticks_evict_with_anomaly_debounce(self):
        """Ticks 1-2 observe the overload (debounce: no eviction); tick 3
        confirms the anomaly and drives jobs through the
        MigrationController into real evictions."""
        nodes = _cluster()
        evictor = PodEvictor()
        migration = MigrationController(
            args=MigrationControllerArgs(
                default_job_mode="EvictDirectly",
                max_concurrent_reclaims_per_node=2,
            ),
            evict=lambda pod: evictor.evict(
                pod, pod.get("node", ""), reason="migration"
            ),
        )
        # ticks 10s apart, inside the 60s anomaly generation window
        clock = iter([100.0, 110.0, 120.0]).__next__
        d = Descheduler(
            [_profile(consecutive=2)],
            nodes_fn=lambda: nodes,
            evictor=evictor,
            migration=migration,
            clock=clock,
        )

        # the reference condition is consecutiveAbnormalities > N
        # (filterRealAbnormalNodes, low_node_load.go:273): with N=2 the
        # detector arms on ticks 1-2 and trips on tick 3
        for tick in (1, 2):
            status = d.descheduler_once()
            assert status.ok
            assert evictor.total_evicted() == 0, f"tick {tick} must debounce"
            assert not migration.jobs

        status = d.descheduler_once()
        assert status.ok
        # anomaly confirmed -> LowNodeLoad plans evictions, the
        # MigrationController arbitrates (2 per node cap) and evicts
        assert migration.jobs, "expected PodMigrationJobs"
        assert evictor.total_evicted() == 2  # per-node concurrency cap
        assert all(r.node == "hot" for r in evictor.evicted)

    def test_single_node_cluster_aborts_tick(self):
        d = Descheduler(
            [_profile()],
            nodes_fn=lambda: [_cluster()[0]],
        )
        status = d.descheduler_once()
        assert not status.ok
        assert "cluster size" in status.err

    def test_node_selector_and_unschedulable_filtered(self):
        nodes = _cluster()
        nodes[1]["unschedulable"] = True
        nodes[2]["labels"] = {"pool": "other"}
        d = Descheduler(
            [_profile()],
            nodes_fn=lambda: nodes,
            node_selector={"pool": "web"},
        )
        assert len(d._ready_nodes()) == 0

    def test_deschedule_plugins_run_before_balance(self):
        order = []

        def desched_factory(fw, args):
            return lambda nodes: order.append("deschedule")

        def balance_factory(fw, args):
            return lambda nodes: order.append("balance")

        registry = {"D": desched_factory, "B": balance_factory}
        profiles = [
            DeschedulerProfile(name="p1", plugins=PluginSet(deschedule=["D"], balance=["B"])),
            DeschedulerProfile(name="p2", plugins=PluginSet(deschedule=["D"], balance=["B"])),
        ]
        d = Descheduler(profiles, nodes_fn=_cluster, registry=registry)
        assert d.descheduler_once().ok
        # ALL deschedule phases precede ANY balance phase (descheduler.go:271-283)
        assert order == ["deschedule", "deschedule", "balance", "balance"]

    def test_plugin_error_aggregated_not_fatal_to_others(self):
        ran = []

        def boom(fw, args):
            def run(nodes):
                raise RuntimeError("boom")

            return run

        def ok_plugin(fw, args):
            return lambda nodes: ran.append(True)

        registry = {"Boom": boom, "OK": ok_plugin}
        d = Descheduler(
            [
                DeschedulerProfile(
                    plugins=PluginSet(deschedule=["Boom", "OK"], balance=[])
                )
            ],
            nodes_fn=_cluster,
            registry=registry,
        )
        status = d.descheduler_once()
        assert not status.ok and "Boom" in status.err
        assert ran == [True], "later plugins still ran (error aggregation)"

    def test_start_runs_once_with_zero_interval(self):
        calls = []

        def factory(fw, args):
            return lambda nodes: calls.append(1)

        d = Descheduler(
            [DeschedulerProfile(plugins=PluginSet(balance=["P"]))],
            nodes_fn=_cluster,
            registry={"P": factory},
            descheduling_interval=0,
        )
        d.start()
        assert len(calls) == 1

    def test_start_ticks_at_interval(self):
        calls = []
        slept = []

        def factory(fw, args):
            return lambda nodes: calls.append(1)

        d = Descheduler(
            [DeschedulerProfile(plugins=PluginSet(balance=["P"]))],
            nodes_fn=_cluster,
            registry={"P": factory},
            descheduling_interval=120.0,
        )
        d.start(max_ticks=3, sleep=slept.append)
        assert len(calls) == 3
        assert slept == [120.0, 120.0]

    def test_unknown_plugin_rejected(self):
        with pytest.raises(ValueError, match="unknown balance plugin"):
            Descheduler(
                [DeschedulerProfile(plugins=PluginSet(balance=["Nope"]))],
                nodes_fn=_cluster,
            )

    def test_restart_adaptor_plugin_evicts_through_framework(self):
        nodes = _cluster()
        nodes[0]["pods"][0]["containers"] = [{"restart_count": 200}]
        evictor = PodEvictor()
        d = Descheduler(
            [
                DeschedulerProfile(
                    plugins=PluginSet(
                        deschedule=["RemovePodsHavingTooManyRestarts"],
                        balance=[],
                    )
                )
            ],
            nodes_fn=lambda: nodes,
            evictor=evictor,
        )
        assert d.descheduler_once().ok
        assert [r.pod for r in evictor.evicted] == ["hot-0"]
