"""Kubelet stub sync surface + reservation-as-pod scheduling path.

Reference: ``statesinformer/impl/kubelet_stub.go`` (pod list from the
kubelet endpoint) and ``frameworkext/eventhandlers/reservation_handler.go``
(Reservations enqueued as reserve pods; binding marks them Available).
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import numpy as np

from koordinator_tpu.koordlet.kubelet_stub import KubeletStub
from koordinator_tpu.model import encode_snapshot
from koordinator_tpu.scheduler.reservation_controller import (
    AVAILABLE,
    Reservation,
    ReservationController,
)
from koordinator_tpu.solver import run_cycle


class TestKubeletStub:
    def test_pod_list_with_bearer_token(self):
        seen = {}

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                seen["auth"] = self.headers.get("Authorization")
                seen["path"] = self.path
                body = json.dumps(
                    {"items": [{"metadata": {"name": "p1"}}]}
                ).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        httpd = HTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            stub = KubeletStub(
                port=httpd.server_address[1], scheme="http", token="tok123"
            )
            pods = stub.get_all_pods()
            assert pods == [{"metadata": {"name": "p1"}}]
            assert seen["auth"] == "Bearer tok123"
            assert seen["path"] == "/pods"
        finally:
            httpd.shutdown()
            httpd.server_close()


class TestReservationAsPod:
    def test_pending_reservation_scheduled_and_available(self):
        """The full reservation-as-pod flow: a Pending reservation enters
        the cycle as a reserve pod, the solver places it, and the
        controller marks it Available on the chosen node."""
        c = ReservationController(clock=lambda: 0.0)
        c.create(
            Reservation(
                name="r1",
                requests={"cpu": "4000m", "memory": "8192Mi"},
                owners=[{"label_selector": {"app": "web"}}],
                ttl_seconds=None,
            )
        )
        reserve_pods = c.pending_reserve_pods()
        assert len(reserve_pods) == 1
        assert (
            reserve_pods[0]["annotations"][
                "scheduling.koordinator.sh/reserve-pod"
            ]
            == "true"
        )

        nodes = [
            {
                "name": f"n{i}",
                "allocatable": {"cpu": "8000m", "memory": "32768Mi", "pods": 110},
                "usage": {"cpu": f"{1000 * (i + 1)}m", "memory": "4096Mi"},
            }
            for i in range(3)
        ]
        snap = encode_snapshot(nodes, reserve_pods)
        result = run_cycle(snap)
        chosen = int(np.asarray(result.assignment)[0])
        assert chosen >= 0

        c.on_reserve_pod_assigned("r1", nodes[chosen]["name"])
        r = c.reservations["r1"]
        assert r.phase == AVAILABLE
        assert r.node == nodes[chosen]["name"]
        # it now feeds the next cycle's ReservationTable
        assert c.active_reservations()[0]["node"] == nodes[chosen]["name"]

    def test_available_reservations_not_reenqueued(self):
        c = ReservationController(clock=lambda: 0.0)
        c.create(Reservation(name="r1", requests={"cpu": "1"}, ttl_seconds=None))
        c.mark_available("r1", "n0")
        assert c.pending_reserve_pods() == []


class TestInformerKubeletSync:
    def test_sync_from_kubelet_refreshes_pod_view(self):
        class FakeStub:
            def get_all_pods(self):
                return [
                    {
                        "metadata": {
                            "name": "p1",
                            "uid": "u1",
                            "namespace": "ns",
                            "labels": {"koordinator.sh/qosClass": "BE"},
                        },
                        "status": {"qosClass": "BestEffort"},
                        "spec": {"nodeName": "n0"},
                    }
                ]

        from koordinator_tpu.koordlet.statesinformer import StatesInformer

        informer = StatesInformer()
        events = []
        informer.register_callback(events.append)
        assert informer.sync_from_kubelet(FakeStub()) == 1
        (pod,) = informer.get_all_pods()
        assert (pod.name, pod.uid, pod.qos, pod.koord_qos, pod.namespace) == (
            "p1", "u1", "BestEffort", "BE", "ns"
        )
        assert informer.get_pod_spec("u1") == {"nodeName": "n0"}
        assert "pods" in events
