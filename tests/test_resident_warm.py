"""The warm-cycle fast path: device-resident snapshot + on-device deltas.

The exactness contract (ISSUE 1): after ANY sequence of warm syncs —
sparse deltas scattered into the resident device tensors, single-tensor
re-uploads, derived-column rebuilds — the resident snapshot must be
bit-identical in effect to a cold re-encode of the same logical state.
The fuzz here drives random delta/full/scalar/resize sequences through a
ScorerServicer and checks assignments AND scores against a cold oracle
on the scan path (and the interpret-mode Pallas kernel for a subset).

Also covered: the per-boot epoch in snapshot ids (a restarted sidecar
must never pass the delta-continuity check, ADVICE r5), companion-array
resets on table resizes, and the persistent compile cache (a second
process reuses the first's cache entry).
"""

import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

from koordinator_tpu.bridge.codegen import pb2
from koordinator_tpu.bridge.server import ScorerServicer
from koordinator_tpu.bridge.state import ResidentState, numpy_to_tensor

R = 13


def _full_sync_request(state: dict) -> "pb2.SyncRequest":
    """Encode the WHOLE logical state as one cold SyncRequest."""
    req = pb2.SyncRequest()
    req.nodes.allocatable.CopyFrom(numpy_to_tensor(state["node_alloc"]))
    req.nodes.requested.CopyFrom(numpy_to_tensor(state["node_requested"]))
    req.nodes.usage.CopyFrom(numpy_to_tensor(state["node_usage"]))
    req.nodes.metric_fresh.extend(bool(b) for b in state["node_fresh"])
    req.pods.requests.CopyFrom(numpy_to_tensor(state["pod_requests"]))
    req.pods.estimated.CopyFrom(numpy_to_tensor(state["pod_estimated"]))
    req.pods.priority.extend(int(v) for v in state["pod_priority"])
    req.pods.gang_id.extend(int(v) for v in state["pod_gang"])
    req.pods.quota_id.extend(int(v) for v in state["pod_quota"])
    req.gangs.min_member.extend(int(v) for v in state["gang_min"])
    if state["quota_runtime"] is not None:
        req.quotas.runtime.CopyFrom(numpy_to_tensor(state["quota_runtime"]))
        req.quotas.used.CopyFrom(numpy_to_tensor(state["quota_used"]))
        req.quotas.limited.CopyFrom(numpy_to_tensor(state["quota_limited"]))
    return req


def _random_state(rng, n_nodes, n_pods, with_quota):
    alloc = rng.randint(4000, 64000, size=(n_nodes, R)).astype(np.int64)
    state = {
        "node_alloc": alloc,
        "node_requested": rng.randint(0, 2000, (n_nodes, R)).astype(np.int64),
        "node_usage": rng.randint(0, 3000, (n_nodes, R)).astype(np.int64),
        "node_fresh": rng.rand(n_nodes) > 0.2,
        "pod_requests": rng.randint(1, 4000, (n_pods, R)).astype(np.int64),
        "pod_estimated": rng.randint(1, 4000, (n_pods, R)).astype(np.int64),
        "pod_priority": rng.randint(0, 9999, n_pods).astype(np.int64),
        "pod_gang": np.where(
            rng.rand(n_pods) > 0.5, rng.randint(0, 2, n_pods), -1
        ).astype(np.int32),
        "pod_quota": -np.ones(n_pods, np.int32),
        "gang_min": np.asarray([2, 3], np.int32),
        "quota_runtime": None,
        "quota_used": None,
        "quota_limited": None,
    }
    if with_quota:
        q = 3
        state["quota_runtime"] = rng.randint(
            5000, 500000, (q, R)
        ).astype(np.int64)
        state["quota_used"] = rng.randint(0, 4000, (q, R)).astype(np.int64)
        state["quota_limited"] = (rng.rand(q, R) > 0.5).astype(np.int64)
        state["pod_quota"] = np.where(
            rng.rand(n_pods) > 0.4, rng.randint(0, q, n_pods), -1
        ).astype(np.int32)
    return state


def _mutate(rng, state):
    """One warm step on the logical state; returns the SyncRequest that a
    delta-aware client would ship (changed tensors only, sparse where
    few cells moved) plus whether any node/pod resize happened."""
    req = pb2.SyncRequest()
    resized = False
    choice = rng.rand()
    if choice < 0.12:
        # resize the node table (full tensors, omitted companions)
        n_old = len(state["node_fresh"])
        n_new = int(rng.randint(3, 12))
        state["node_alloc"] = rng.randint(
            4000, 64000, (n_new, R)
        ).astype(np.int64)
        req.nodes.allocatable.CopyFrom(numpy_to_tensor(state["node_alloc"]))
        if n_new != n_old and rng.rand() < 0.4:
            # a resize frame may legally carry ONLY allocatable: the
            # server resets the omitted old-shaped requested/usage
            # mirrors to defaults of the new shape (zeros).  The client
            # has no acked baseline for them anymore — its next update
            # of those tensors must ship full, so flag them.
            state["node_requested"] = np.zeros((n_new, R), np.int64)
            state["node_usage"] = np.zeros((n_new, R), np.int64)
            state.setdefault("_no_baseline", set()).update(
                {"node_requested", "node_usage"}
            )
        else:
            state["node_requested"] = rng.randint(
                0, 2000, (n_new, R)
            ).astype(np.int64)
            state["node_usage"] = rng.randint(
                0, 3000, (n_new, R)
            ).astype(np.int64)
            req.nodes.requested.CopyFrom(
                numpy_to_tensor(state["node_requested"])
            )
            req.nodes.usage.CopyFrom(numpy_to_tensor(state["node_usage"]))
        if n_new != n_old:
            # row-count change: the server resets the omitted freshness
            # companion to its default (all fresh); an equal-size full
            # sync keeps the resident column (empty repeated = unchanged)
            state["node_fresh"] = np.ones(n_new, bool)
        resized = True
        return req, resized
    # sparse mutations on a random subset of tensors
    for key, wire in (
        ("node_requested", req.nodes.requested),
        ("node_usage", req.nodes.usage),
        ("pod_requests", req.pods.requests),
        ("pod_estimated", req.pods.estimated),
        ("quota_used", req.quotas.used),
    ):
        arr = state[key]
        if arr is None or rng.rand() > 0.55:
            continue
        prev = arr.copy()
        cells = rng.randint(1, max(2, arr.size // 8))
        flat = arr.reshape(-1)
        idx = rng.choice(arr.size, size=cells, replace=False)
        flat[idx] = rng.randint(0, 5000, cells)
        if key in state.get("_no_baseline", ()):
            # the server reset this mirror on a resize: no delta
            # baseline exists, the update must ride full once
            state["_no_baseline"].discard(key)
            prev = None
        wire.CopyFrom(numpy_to_tensor(arr, prev))
    if rng.rand() < 0.25:
        # scalar column churn: freshness and priorities
        state["node_fresh"] = rng.rand(len(state["node_fresh"])) > 0.2
        req.nodes.metric_fresh.extend(bool(b) for b in state["node_fresh"])
    if rng.rand() < 0.2:
        state["pod_priority"] = rng.randint(
            0, 9999, len(state["pod_priority"])
        ).astype(np.int64)
        req.pods.priority.extend(int(v) for v in state["pod_priority"])
    return req, resized


def _cold_oracle(state) -> ScorerServicer:
    sv = ScorerServicer()
    sv.sync(_full_sync_request(state))
    return sv


def _results(sv: ScorerServicer):
    """Cycle + score outputs over the VALID region.  Pad buckets are a
    physical detail the warm path may legitimately carry sticky across a
    shrink (avoiding a recompile) while a cold re-encode picks the
    smallest bucket — the exactness contract is over real rows/columns,
    where both must agree bit-for-bit."""
    from koordinator_tpu.solver import greedy_assign, score_cycle

    snap = sv.state.snapshot()
    N = int(np.asarray(snap.nodes.valid).sum())
    P = int(np.asarray(snap.pods.valid).sum())
    cycle = greedy_assign(snap)
    scores, feasible = score_cycle(snap)
    return (
        np.asarray(cycle.assignment)[:P],
        np.asarray(cycle.status)[:P],
        np.asarray(cycle.quota_used),
        np.asarray(scores)[:P, :N],
        np.asarray(feasible)[:P, :N],
    )


class TestWarmParityFuzz:
    @pytest.mark.parametrize("seed", range(6))
    def test_delta_sequences_match_cold_reencode(self, seed):
        rng = np.random.RandomState(seed)
        state = _random_state(
            rng, n_nodes=int(rng.randint(4, 10)),
            n_pods=int(rng.randint(8, 24)),
            with_quota=bool(seed % 2),
        )
        warm = ScorerServicer()
        warm.sync(_full_sync_request(state))
        warm_seen = False
        for cycle in range(10):
            # materialize the resident snapshot so warm updates have a
            # target (a real server does this at the first Score/Assign)
            warm.state.snapshot()
            req, _resized = _mutate(rng, state)
            warm.sync(req)
            warm_seen = warm_seen or warm.state.last_sync_path == "warm"
            got = _results(warm)
            want = _results(_cold_oracle(state))
            for g, w, name in zip(
                got, want, ("assignment", "status", "quota_used",
                            "scores", "feasible")
            ):
                np.testing.assert_array_equal(
                    g, w, err_msg=f"seed={seed} cycle={cycle} {name}"
                )
        assert warm_seen, "fuzz never exercised the warm device path"

    @pytest.mark.parametrize("seed", (0, 1))
    def test_warm_resident_matches_cold_on_pallas_interpret(self, seed):
        """The resident-device snapshot feeds the Pallas kernel too: the
        interpret-mode kernel must produce the same placements from the
        warm-updated arrays as from a cold re-encode."""
        from koordinator_tpu.solver.pallas_cycle import greedy_assign_pallas

        rng = np.random.RandomState(100 + seed)
        state = _random_state(rng, n_nodes=6, n_pods=16, with_quota=True)
        warm = ScorerServicer()
        warm.sync(_full_sync_request(state))
        for _ in range(3):
            warm.state.snapshot()
            req, _ = _mutate(rng, state)
            warm.sync(req)
        warm_res = greedy_assign_pallas(
            warm.state.snapshot(), interpret=True
        )
        cold_res = greedy_assign_pallas(
            _cold_oracle(state).state.snapshot(), interpret=True
        )
        np.testing.assert_array_equal(
            np.asarray(warm_res.assignment), np.asarray(cold_res.assignment)
        )
        np.testing.assert_array_equal(
            np.asarray(warm_res.status), np.asarray(cold_res.status)
        )


class TestResidentMechanics:
    def _base_state(self):
        rng = np.random.RandomState(7)
        return _random_state(rng, n_nodes=4, n_pods=8, with_quota=False)

    def test_delta_sync_updates_device_in_place(self):
        state = self._base_state()
        sv = ScorerServicer()
        sv.sync(_full_sync_request(state))
        snap1 = sv.state.snapshot()
        assert sv.state.last_sync_path == "cold"

        prev = state["node_requested"].copy()
        state["node_requested"][0, 0] += 111
        req = pb2.SyncRequest()
        req.nodes.requested.CopyFrom(
            numpy_to_tensor(state["node_requested"], prev)
        )
        assert req.nodes.requested.delta_idx  # rode the wire as a delta
        sv.sync(req)
        assert sv.state.last_sync_path == "warm"
        snap2 = sv.state.snapshot()
        # untouched tensors keep their resident device buffers
        assert snap2.nodes.allocatable is snap1.nodes.allocatable
        assert snap2.pods.requests is snap1.pods.requests
        # the touched one took the scatter
        got = np.asarray(snap2.nodes.requested)
        assert got[0, 0] == state["node_requested"][0, 0]

    def test_resize_drops_residency_and_resets_companions(self):
        """ADVICE r5: a full sync that changes the node/pod table size
        while omitting companion columns must reset them to defaults of
        the new shape — and the snapshot build must succeed."""
        state = self._base_state()
        sv = ScorerServicer()
        sv.sync(_full_sync_request(state))
        sv.state.snapshot()

        # grow nodes 4 -> 9 with ONLY the three node tensors on the wire
        rng = np.random.RandomState(8)
        grown = rng.randint(4000, 64000, (9, R)).astype(np.int64)
        req = pb2.SyncRequest()
        req.nodes.allocatable.CopyFrom(numpy_to_tensor(grown))
        req.nodes.requested.CopyFrom(
            numpy_to_tensor(np.zeros((9, R), np.int64))
        )
        req.nodes.usage.CopyFrom(numpy_to_tensor(np.zeros((9, R), np.int64)))
        sv.sync(req)
        assert sv.state.last_sync_path == "cold"
        # stale 4-row freshness column was reset, not left to fail here
        assert sv.state.node_fresh is None
        snap = sv.state.snapshot()
        assert snap.nodes.allocatable.shape[0] >= 9
        assert int(np.asarray(snap.nodes.valid).sum()) == 9

        # shrink pods 8 -> 3 omitting priorities/gangs/estimated: same deal
        preq = rng.randint(1, 4000, (3, R)).astype(np.int64)
        req = pb2.SyncRequest()
        req.pods.requests.CopyFrom(numpy_to_tensor(preq))
        sv.sync(req)
        assert sv.state.pod_priority is None
        assert sv.state.pod_estimated is None  # defaults to requests
        snap = sv.state.snapshot()
        assert int(np.asarray(snap.pods.valid).sum()) == 3
        np.testing.assert_array_equal(
            np.asarray(snap.pods.estimated)[:3], preq
        )

    def test_duplicate_delta_indices_rejected(self):
        """Duplicate flat indices must bounce the frame: host apply is
        sequential last-wins but device scatter duplicates are
        implementation-defined — accepting them could silently split the
        mirror from the resident tensors."""
        state = self._base_state()
        sv = ScorerServicer()
        sv.sync(_full_sync_request(state))
        bad = pb2.SyncRequest()
        bad.nodes.usage.shape.extend(state["node_usage"].shape)
        bad.nodes.usage.delta_idx = np.asarray([5, 5], "<i8").tobytes()
        bad.nodes.usage.delta_val = np.asarray([100, 200], "<i8").tobytes()
        before = sv.state.node_usage.copy()
        with pytest.raises(ValueError, match="duplicate"):
            sv.state.apply_sync(bad)
        np.testing.assert_array_equal(sv.state.node_usage, before)

    def test_resize_frame_with_stale_companion_tensor_rejected(self):
        """A resize frame carrying a companion tensor still shaped for
        the PRE-resize table (a delta validated against the old resident
        base, or an old-shaped full) must bounce whole — committing it
        would silently pad stale rows onto the new table."""
        state = self._base_state()
        sv = ScorerServicer()
        sv.sync(_full_sync_request(state))
        rng = np.random.RandomState(11)

        bad = pb2.SyncRequest()
        bad.nodes.allocatable.CopyFrom(
            numpy_to_tensor(rng.randint(4000, 64000, (9, R)).astype(np.int64))
        )
        # delta against the OLD 4-row requested mirror rides the same frame
        stale = state["node_requested"].copy()
        stale[0, 0] += 1
        bad.nodes.requested.CopyFrom(
            numpy_to_tensor(stale, state["node_requested"])
        )
        assert bad.nodes.requested.delta_idx
        before = sv.state.node_alloc.copy()
        with pytest.raises(ValueError, match="pre-resize"):
            sv.state.apply_sync(bad)
        np.testing.assert_array_equal(sv.state.node_alloc, before)

    def test_pod_resize_keeps_gang_table(self):
        """The gang table is per-gang, not per-pod: a pod-table resize
        frame that omits the unchanged gangs.min_member field must keep
        the resident gang table (a reset would silently disable gang
        gating while the new pods' gang_id column still references it)."""
        state = self._base_state()
        sv = ScorerServicer()
        sv.sync(_full_sync_request(state))
        before = sv.state.gang_min.copy()

        rng = np.random.RandomState(9)
        req = pb2.SyncRequest()
        req.pods.requests.CopyFrom(
            numpy_to_tensor(rng.randint(1, 4000, (5, R)).astype(np.int64))
        )
        req.pods.gang_id.extend([0, 0, 1, 1, 1])
        sv.sync(req)
        np.testing.assert_array_equal(sv.state.gang_min, before)
        snap = sv.state.snapshot()
        assert bool(np.asarray(snap.gangs.valid)[:2].all())
        np.testing.assert_array_equal(
            np.asarray(snap.gangs.min_member)[:2], before
        )

    def test_omitted_buckets_inherit_resident_bucket(self):
        """A warm frame without explicit buckets must not recompute a
        different pad bucket (that would reshape — and recompile — the
        resident snapshot mid-stream)."""
        state = self._base_state()
        req = _full_sync_request(state)
        req.node_bucket = 5  # explicit non-power-of-two cold bucket
        sv = ScorerServicer()
        sv.sync(req)
        sv.state.snapshot()
        assert sv.state.node_bucket == 5

        prev = state["node_usage"].copy()
        state["node_usage"][1, 2] += 7
        warm = pb2.SyncRequest()
        warm.nodes.usage.CopyFrom(numpy_to_tensor(state["node_usage"], prev))
        sv.sync(warm)  # no bucket fields on the wire
        assert sv.state.node_bucket == 5
        assert sv.state.last_sync_path == "warm"


class TestEpochContinuity:
    def test_parse_snapshot_id_forms(self):
        from koordinator_tpu.bridge.plugin_sim import (
            generation,
            parse_snapshot_id,
        )

        assert parse_snapshot_id("sabc123-7") == ("abc123", 7)
        assert parse_snapshot_id("s42") == ("", 42)
        assert parse_snapshot_id("junk") == ("", -1)
        assert parse_snapshot_id("sabc-def") == ("abc", -1)
        assert generation("sabc123-7") == 7

    def test_server_epoch_in_snapshot_id_and_check(self):
        state = np.random.RandomState(3)
        s1, s2 = ScorerServicer(), ScorerServicer()
        assert s1._epoch != s2._epoch  # per-boot nonce
        st = _random_state(state, 4, 8, False)
        r1 = s1.sync(_full_sync_request(st))
        assert r1.snapshot_id == f"s{s1._epoch}-1"
        s1.assign(pb2.AssignRequest(snapshot_id=r1.snapshot_id))
        # a bare legacy "s<gen>" id is rejected: accepting it would
        # re-open the restart-coincidence hole for Score/Assign
        with pytest.raises(ValueError, match="not resident"):
            s1.assign(pb2.AssignRequest(snapshot_id="s1"))
        # a different boot's id is NOT resident here
        s2.sync(_full_sync_request(st))
        with pytest.raises(ValueError, match="not resident"):
            s2.assign(pb2.AssignRequest(snapshot_id=r1.snapshot_id))

    def test_restart_with_coincident_generation_forces_full_resync(self):
        """The trap the epoch closes (ADVICE r5): after a sidecar restart
        the generation counter restarts, so a foreign full sync can put
        the new boot EXACTLY at mirror.gen+1 for our next delta — the
        arithmetic check alone would silently land our deltas on the
        foreign baseline.  The epoch mismatch must force a full re-sync."""
        from koordinator_tpu.bridge.plugin_sim import GoPluginSim, NUM_AXES
        from koordinator_tpu.bridge.udsserver import RawUdsServer

        def vec(cpu=0, mem=0, pods=0):
            v = [0] * NUM_AXES
            v[0], v[1], v[3] = cpu, mem, pods
            return v

        alloc, reqv, pod = vec(8000, 16384, 110), vec(1000, 1024, 5), vec(500, 512, 1)
        nodes = [("node-a", alloc, reqv), ("node-b", alloc, reqv)]
        path = os.path.join(tempfile.mkdtemp(), "scorer.sock")
        srv = RawUdsServer(path).start()
        sim = GoPluginSim(path)
        sim.pre_score(nodes, "pod-1", pod)  # mirror: epoch A, gen 1
        assert sim.mirror.epoch and sim.mirror.gen == 1
        srv.stop()

        # fresh boot (epoch B); a FOREIGN client syncs a same-shaped but
        # different-valued node table, putting the new boot at gen 1
        srv2 = RawUdsServer(path).start()
        try:
            foreign = GoPluginSim(path)
            hot = vec(cpu=7777, mem=9999, pods=50)
            foreign.pre_score(
                [("node-a", alloc, hot), ("node-b", alloc, hot)],
                "foreign-pod", pod,
            )
            # our connection died with the old boot; reconnect cleanly so
            # the delta sync itself SUCCEEDS (the dangerous case — e.g. a
            # socket-activated listener keeps the dial working)
            sim._drop_client()
            sim.sent_frames.clear()
            scores = sim.pre_score(nodes, "pod-2", pod)
            # delta sync (gen 2 == mirror.gen+1 arithmetically!) + the
            # epoch-forced full re-sync + score
            methods = [m for m, _ in sim.sent_frames]
            assert methods == [1, 1, 2]
            assert sim.sent_frames[1][1] > sim.sent_frames[0][1]
            cold = GoPluginSim(path)
            assert cold.pre_score(nodes, "pod-2", pod) == scores
        finally:
            srv2.stop()


class TestRetraceBudget:
    """ISSUE 2: the warm path's compile economics, locked in at test
    time.  After one warm-up cycle has compiled every program the warm
    loop touches (the bucket-1 scatter, the cycle scan), a steady
    delta-Sync/Assign sequence must run with ZERO jit cache misses —
    any retrace means static metadata or geometry leaked into the trace
    signature (the PR-1 name-tuple bug class)."""

    def _warm_step(self, sv, state):
        prev = state["node_usage"].copy()
        state["node_usage"][0, 1] += 1
        req = pb2.SyncRequest()
        req.nodes.usage.CopyFrom(numpy_to_tensor(state["node_usage"], prev))
        assert req.nodes.usage.delta_idx  # rides as a sparse delta
        sv.sync(req)
        assert sv.state.last_sync_path == "warm"
        return sv.assign(pb2.AssignRequest(snapshot_id=sv.snapshot_id()))

    def test_warm_sync_assign_sequence_is_retrace_free(self):
        from koordinator_tpu.analysis import retrace_guard

        rng = np.random.RandomState(21)
        state = _random_state(rng, n_nodes=5, n_pods=12, with_quota=False)
        sv = ScorerServicer()
        sv.sync(_full_sync_request(state))
        sv.state.snapshot()
        # warm-up cycle: compiles the delta scatter + the cycle program
        first = self._warm_step(sv, state)
        with retrace_guard(budget=0) as counter:
            for _ in range(4):
                reply = self._warm_step(sv, state)
        assert counter.traces == 0 and counter.compiles == 0
        assert len(reply.assignment) == len(first.assignment)

    def test_warm_sync_wave_assign_sequence_is_retrace_free(self):
        """The wave-batched cycle holds the same compile economics: with
        wave/top_m riding the STATIC CycleConfig, a warm delta-Sync +
        wave-Assign stream must hit zero jit cache misses after one
        warm-up cycle (a traced wave width would retrace every cycle —
        the hazard the koordlint rule rejects statically)."""
        from koordinator_tpu.analysis import retrace_guard
        from koordinator_tpu.config import CycleConfig

        rng = np.random.RandomState(23)
        state = _random_state(rng, n_nodes=5, n_pods=12, with_quota=False)
        sv = ScorerServicer(CycleConfig(wave=8, top_m=2))
        sv.sync(_full_sync_request(state))
        sv.state.snapshot()
        first = self._warm_step(sv, state)
        with retrace_guard(budget=0) as counter:
            for _ in range(4):
                reply = self._warm_step(sv, state)
        assert counter.traces == 0 and counter.compiles == 0
        assert reply.path == "wave"
        assert len(reply.assignment) == len(first.assignment)

    def test_warm_sequence_with_telemetry_enabled_is_retrace_free(self):
        """ISSUE 4 acceptance: telemetry is ALWAYS on in the servicer
        (spans, scorer metric families, flight ring), and the warm
        delta-Sync/Assign stream still holds ZERO jit cache misses —
        instrumentation must live entirely outside the traced programs.
        The same stream must actually POPULATE the telemetry: a
        zero-overhead subsystem that recorded nothing would pass
        vacuously."""
        import tempfile

        from koordinator_tpu.analysis import retrace_guard
        from koordinator_tpu.obs import validate_flight_dump

        rng = np.random.RandomState(29)
        state = _random_state(rng, n_nodes=5, n_pods=12, with_quota=False)
        sv = ScorerServicer(state_dir=tempfile.mkdtemp())
        sv.sync(_full_sync_request(state))
        sv.state.snapshot()
        self._warm_step(sv, state)  # warm-up compiles
        reg = sv.telemetry.registry
        miss_before = reg.get(
            "koord_scorer_jit_cache_miss_total", {"kind": "trace"}
        ) or 0
        with retrace_guard(budget=0) as counter:
            for _ in range(4):
                reply = self._warm_step(sv, state)
        assert counter.traces == 0 and counter.compiles == 0
        # the process-wide miss counter agrees with the guard: no new
        # misses landed during the telemetry-enabled warm stream
        miss_after = reg.get(
            "koord_scorer_jit_cache_miss_total", {"kind": "trace"}
        ) or 0
        assert miss_after == miss_before
        # ... and the stream populated the families + the flight ring
        count, _total = reg.get_histogram(
            "koord_scorer_cycle_latency_ms",
            {"path": reply.path, "wave": "1"},
        )
        assert count >= 4
        assert reg.get("koord_scorer_sync_total", {"kind": "delta"}) >= 4
        records = sv.telemetry.flight.snapshot()
        assert len(records) >= 4
        names = [s["name"] for s in records[-1]["spans"]]
        assert "sync_decode" in names and "delta_scatter" in names
        assert "dispatch" in names and "readback" in names
        # the ring dumps schema-valid under the guard's own contract
        assert validate_flight_dump(
            sv.telemetry.flight.document("test")
        ) == []

    def test_warm_mesh_sharded_stream_is_retrace_free(self):
        """ISSUE 7 acceptance: the MESH-SHARDED resident snapshot keeps
        the zero-retrace invariant per shard.  A warm delta-Sync /
        Score / shard-Assign stream against a snapshot sharded over all
        8 forced-host devices must hit zero jit cache misses after one
        warm-up cycle — the shard-local scatter compiles once per
        (shape, bucket, mesh), the cross-shard top-M merge rides the
        static (cfg, mesh, wave, top_m) key, and the in/out sharding
        match means no hidden resharding programs are minted."""
        import jax

        from koordinator_tpu.analysis import retrace_guard
        from koordinator_tpu.parallel import cluster_mesh

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 (virtual) devices")
        rng = np.random.RandomState(37)
        state = _random_state(rng, n_nodes=5, n_pods=12, with_quota=False)
        sv = ScorerServicer(
            mesh=cluster_mesh(jax.devices()), mesh_resident=True
        )
        sv.sync(_full_sync_request(state))
        snap = sv.state.snapshot()
        # really sharded: node rows split over all 8 devices
        assert len(snap.nodes.allocatable.sharding.device_set) == 8
        # warm-up compiles: sharded scatter, score/top_k, shard cycle
        sv.score(pb2.ScoreRequest(
            snapshot_id=sv.snapshot_id(), top_k=3, flat=True
        ))
        first = self._warm_step(sv, state)
        assert first.path == "shard"
        # ... and the shard-local incremental rescore (ISSUE 9): the
        # first WARM Score advances the resident score tensors through
        # the dirty-column kernel, whose compile belongs to warm-up
        sv.score(pb2.ScoreRequest(
            snapshot_id=sv.snapshot_id(), top_k=3, flat=True
        ))
        with retrace_guard(budget=0) as counter:
            for _ in range(4):
                prev = state["node_usage"].copy()
                state["node_usage"][0, 1] += 1
                req = pb2.SyncRequest()
                req.nodes.usage.CopyFrom(
                    numpy_to_tensor(state["node_usage"], prev)
                )
                sv.sync(req)
                assert sv.state.last_sync_path == "warm"
                sv.score(pb2.ScoreRequest(
                    snapshot_id=sv.snapshot_id(), top_k=3, flat=True
                ))
                reply = sv.assign(
                    pb2.AssignRequest(snapshot_id=sv.snapshot_id())
                )
        assert counter.traces == 0 and counter.compiles == 0
        assert reply.path == "shard"

    def test_warm_stream_with_coalesced_score_is_retrace_free(self):
        """ISSUE 5 acceptance: the coalescing dispatch engine is always
        on in the servicer, and a warm delta-Sync/Score/Assign stream
        through it still holds ZERO jit cache misses — the padded
        ``top_k`` launch (k padded to the sticky power-of-two bucket)
        must not mint new compiled shapes as batch composition varies,
        and the lock split must not reintroduce per-request retraces."""
        from koordinator_tpu.analysis import retrace_guard

        rng = np.random.RandomState(31)
        state = _random_state(rng, n_nodes=5, n_pods=12, with_quota=False)
        sv = ScorerServicer()
        sv.sync(_full_sync_request(state))
        sv.state.snapshot()
        # warm-up: compiles the scatter, the cycle, the score/top_k
        # programs (two top_k values land in the same pad bucket) AND
        # the incremental column rescore (ISSUE 9) — the first Score
        # after a warm delta advances the resident score tensors
        # through the dirty-column kernel
        sv.score(pb2.ScoreRequest(
            snapshot_id=sv.snapshot_id(), top_k=3, flat=True
        ))
        self._warm_step(sv, state)
        sv.score(pb2.ScoreRequest(
            snapshot_id=sv.snapshot_id(), top_k=2, flat=True
        ))
        with retrace_guard(budget=0) as counter:
            for step in range(4):
                prev = state["node_usage"].copy()
                state["node_usage"][0, 1] += 1
                req = pb2.SyncRequest()
                req.nodes.usage.CopyFrom(
                    numpy_to_tensor(state["node_usage"], prev)
                )
                sv.sync(req)
                assert sv.state.last_sync_path == "warm"
                # alternating k within one pad bucket: coalesced batch
                # composition changing must not change compiled shapes
                sv.score(pb2.ScoreRequest(
                    snapshot_id=sv.snapshot_id(),
                    top_k=3 if step % 2 else 2,
                    flat=True,
                ))
                sv.assign(pb2.AssignRequest(snapshot_id=sv.snapshot_id()))
        assert counter.traces == 0 and counter.compiles == 0
        assert sv.dispatch.stats()["batches"] >= 5

    def test_guard_actually_counts(self):
        """Negative control: a fresh jit inside the guard must trip it —
        otherwise a broken counter would pass the budget test vacuously."""
        import jax
        import jax.numpy as jnp

        from koordinator_tpu.analysis import (
            RetraceBudgetExceeded,
            retrace_guard,
        )

        with pytest.raises(RetraceBudgetExceeded, match="retrace budget"):
            with retrace_guard(budget=0) as counter:
                jax.jit(lambda x: x + 1)(jnp.zeros(3))
        assert counter.traces > 0

    def test_guard_is_inert_outside_the_block(self):
        import jax
        import jax.numpy as jnp

        from koordinator_tpu.analysis import retrace_guard

        with retrace_guard(budget=1) as counter:
            pass
        before = counter.traces
        jax.jit(lambda x: x - 1)(jnp.zeros(5))  # after stop(): not counted
        assert counter.traces == before


_CACHE_CHILD = r"""
import logging, os, sys
logging.basicConfig(stream=sys.stderr, level=logging.DEBUG)
logging.getLogger().setLevel(logging.WARNING)
logging.getLogger("jax._src.compiler").setLevel(logging.DEBUG)
import jax
jax.config.update("jax_platforms", "cpu")
import koordinator_tpu  # wires the persistent cache from KOORD_XLA_CACHE
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
import numpy as np
from koordinator_tpu.model import encode_snapshot
from koordinator_tpu.solver import greedy_assign

nodes = [
    {"name": f"n{i}", "allocatable": {"cpu": "8000m", "memory": 1 << 34}}
    for i in range(4)
]
pods = [
    {"name": f"p{i}", "requests": {"cpu": "500m", "memory": 1 << 30}}
    for i in range(8)
]
snap = encode_snapshot(nodes, pods, [], [])
print("ASSIGN", np.asarray(greedy_assign(snap).assignment).tolist())
"""


class TestCompileCacheSmoke:
    def test_second_process_reuses_cache_entry(self, tmp_path):
        """A restarted sidecar must skip the cycle compile: process one
        populates the persistent cache, process two must compile the
        cycle with zero persistent-cache misses and add no new entries."""
        cache = str(tmp_path / "xla-cache")
        env = dict(
            os.environ,
            KOORD_XLA_CACHE=cache,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=1",
        )

        def run():
            return subprocess.run(
                [sys.executable, "-c", _CACHE_CHILD],
                capture_output=True, text=True, timeout=300, env=env,
                cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            )

        p1 = run()
        assert p1.returncode == 0, p1.stderr[-2000:]
        assert "ASSIGN" in p1.stdout
        files1 = sorted(os.listdir(cache))
        assert files1, "first process wrote no cache entries"
        assert "CACHE MISS for 'jit_greedy_assign" in p1.stderr

        p2 = run()
        assert p2.returncode == 0, p2.stderr[-2000:]
        # same logical program -> same cache key: reused, not re-missed
        assert "CACHE MISS for 'jit_greedy_assign" not in p2.stderr
        files2 = sorted(os.listdir(cache))
        assert [f for f in files2 if f not in files1] == []
        assert p2.stdout.splitlines()[-1] == p1.stdout.splitlines()[-1]

    def test_configure_compilation_cache_env_override_wins(self, monkeypatch):
        import jax

        import koordinator_tpu

        before = jax.config.jax_compilation_cache_dir
        monkeypatch.setenv("KOORD_XLA_CACHE", "/elsewhere")
        koordinator_tpu.configure_compilation_cache("/tmp/should-not-win")
        assert jax.config.jax_compilation_cache_dir == before
        monkeypatch.delenv("KOORD_XLA_CACHE")
        koordinator_tpu.configure_compilation_cache(before)
        assert jax.config.jax_compilation_cache_dir == before
